"""Whole-program executor-affinity inference (the pandaraces foundation).

The reference is thread-per-core with no shared-state locking; this build
deliberately trades that for a small zoo of execution contexts — the
asyncio loop, the ``rptpu-coproc-tick`` executor pool, daemon threads
(mask harvester, fetch workers), the host-stage pool workers, and
weakref/atexit finalizers. Every past review-round concurrency bug lived
on a boundary between two of those contexts, so the race and lock-order
checkers need one ground truth: *which contexts can execute each
function*.

This module builds that ground truth for a whole parsed program:

1. **Collection** — every function/method/lambda across all files becomes
   a :class:`ProgFunc`, indexed for name-based call resolution (same
   philosophy as jitgraph.py: a false edge is worse than a missed one for
   a gate people must keep green, so resolution is conservative).
2. **Seeding** at spawn sites:

   - ``async def`` → ``loop`` (the function body runs on the event loop);
   - ``loop.run_in_executor(ex, fn, ...)`` / ``asyncio.to_thread(fn)`` →
     ``executor`` (the coproc-tick pool / default executor);
   - ``Thread(target=fn)`` / a ``threading.Thread`` subclass's ``run`` →
     ``daemon`` (harvester, fetch workers, loadgen fleets);
   - callables handed to a ``*pool*.run([...])`` fan-out or
     ``ex.submit(fn)`` → ``pool_worker`` (HostStagePool shard workers);
     lambdas defined in a function that performs such a fan-out count —
     the engine builds its thunk lists before the ``pool.run`` call;
   - ``weakref.finalize(obj, fn)`` / ``atexit.register(fn)`` →
     ``finalizer``;
   - ``loop.call_soon[_threadsafe]/call_later(fn)`` → ``loop``.

3. **Propagation** over resolved calls: a callee inherits every context
   of every caller (monotone fixpoint). Calls resolve through module
   aliases (``from pkg import mod; mod.fn()``), ``from``-imported
   symbols, ``self.``/``cls.`` methods, bare local names, and — for
   plain ``obj.method()`` — by method name only when exactly ONE class
   in the program defines it (ambiguous names would smear contexts
   across unrelated classes).

Contexts are deliberately coarse: ``loop`` is single-threaded, so two
``loop`` sites never race each other, while ``executor`` and
``pool_worker`` are multi-threaded pools that race *themselves*
(`SELF_RACING`) — the duplicate-jit-trace bug class. ``daemon`` models
one dedicated thread per spawn, racing every *other* context but not
itself.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

# ------------------------------------------------------------ context labels
LOOP = "loop"
EXECUTOR = "executor"
DAEMON = "daemon"
POOL_WORKER = "pool_worker"
FINALIZER = "finalizer"

ALL_CONTEXTS = (LOOP, EXECUTOR, DAEMON, POOL_WORKER, FINALIZER)

# The device-mesh execution context is tracked SEPARATELY from the
# concurrency contexts above (ProgFunc.mesh, not ProgFunc.contexts): a
# function handed to ``shard_map(fn, ...)`` is a trace-time SPMD program
# replicated onto every mesh device — it does not RACE host code (tracing
# happens once, on the caller's thread), it must not TOUCH host state at
# all (host calls run at trace time, not per launch, and host effects
# don't shard). Folding it into the race contexts would smear phantom
# RAC11xx findings across every helper a predicate shares with host
# paths; the meshctx checker (MSH13xx) consumes the separate flag.
DEVICE_MESH = "device_mesh"

# call names that seed the device-mesh context at their first argument
_MESH_SPAWNS = {"shard_map"}

# contexts backed by a multi-threaded pool: two activations of the SAME
# context can run concurrently (the PR-3 duplicate-jit-trace shape)
SELF_RACING = frozenset({EXECUTOR, POOL_WORKER})

# name-based obj.method resolution: give up beyond this many candidate
# classes (lock-graph superset edges only; contexts require uniqueness)
AMBIG_LIMIT = 4

# Lifecycle-phase functions (open / recovery / startup): they execute in
# their spawn context (DiskLog._open_sync runs on the to_thread executor)
# but the object is not yet serving concurrent traffic, so their contexts
# do not PROPAGATE to the steady-state helpers they call — otherwise every
# helper shared between recovery and the serve path reads as cross-context
# and the race checker buries real findings under startup noise. The race
# checker also exempts these functions' own accesses (same rationale as
# __init__). Documented limitation: a genuine open-vs-serve overlap is
# invisible to the analysis.
LIFECYCLE = re.compile(r"(^|_)(start|open|load|recover|rebuild|restore|bootstrap)")

_EXECUTOR_SPAWNS = {"run_in_executor", "to_thread"}
_LOOP_CALLBACKS = {"call_soon", "call_soon_threadsafe", "call_later", "call_at"}
_THREAD_CTORS = {"Thread", "Timer"}


def dotted(node: ast.expr) -> str:
    """'a.b.c' for Name/Attribute chains, '' otherwise."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def modkey_of(relpath: str) -> str:
    """'redpanda_tpu/coproc/engine.py' -> 'redpanda_tpu.coproc.engine'."""
    p = relpath.replace("\\", "/")
    if p.endswith(".py"):
        p = p[:-3]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


def modbase(modkey: str) -> str:
    return modkey.rsplit(".", 1)[-1]


@dataclass
class ProgFunc:
    """One function/method/lambda in the analyzed program."""

    node: ast.AST                 # FunctionDef | AsyncFunctionDef | Lambda
    relpath: str
    modkey: str
    cls: str | None               # enclosing class name (methods + lambdas)
    name: str                     # "<lambda>" for lambdas
    lineno: int
    is_method: bool = False       # a DIRECT class member (not nested)
    contexts: set[str] = field(default_factory=set)
    # device-mesh (shard_map-traced) membership — separate from contexts,
    # see DEVICE_MESH above
    mesh: bool = False

    @property
    def qualname(self) -> str:
        if self.cls:
            return f"{self.cls}.{self.name}"
        return self.name


class Program:
    """Collected functions + call resolution + affinity fixpoint for a
    set of parsed modules ``[(relpath, ast.Module), ...]``."""

    def __init__(self, modules: list[tuple[str, ast.Module]]):
        self.modules = list(modules)
        self.funcs: dict[int, ProgFunc] = {}          # id(node) -> info
        # (modkey, name) -> funcs defined anywhere in that module
        self._local: dict[tuple[str, str], list[ProgFunc]] = {}
        # (modkey, name) -> module-LEVEL functions only
        self._module_level: dict[tuple[str, str], list[ProgFunc]] = {}
        # (class name, method name) -> direct methods, program-wide
        self._methods: dict[tuple[str, str], list[ProgFunc]] = {}
        # method name -> direct methods, program-wide (obj.method fallback)
        self._by_method: dict[str, list[ProgFunc]] = {}
        # class name -> [(modkey, ClassDef)]
        self.classes: dict[str, list[tuple[str, ast.ClassDef]]] = {}
        # modkey -> import alias table:
        #   name -> ("module", target_modkey) | ("symbol", modkey, symbol)
        self._aliases: dict[str, dict[str, tuple]] = {}
        self._known_modkeys: set[str] = {modkey_of(rp) for rp, _ in modules}
        for relpath, tree in self.modules:
            self._collect_module(relpath, tree)
        self._seed()
        self._propagate()

    # ------------------------------------------------------------ collection
    def _collect_module(self, relpath: str, tree: ast.Module) -> None:
        modkey = modkey_of(relpath)
        aliases: dict[str, tuple] = {}
        self._aliases[modkey] = aliases
        program = self

        for node in tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    tgt = a.name
                    if tgt in self._known_modkeys:
                        aliases[a.asname or tgt.rsplit(".", 1)[-1]] = (
                            "module", tgt,
                        )
            elif isinstance(node, ast.ImportFrom) and node.module:
                base = node.module
                for a in node.names:
                    full = f"{base}.{a.name}"
                    if full in self._known_modkeys:
                        aliases[a.asname or a.name] = ("module", full)
                    elif base in self._known_modkeys:
                        aliases[a.asname or a.name] = ("symbol", base, a.name)

        class V(ast.NodeVisitor):
            def __init__(self) -> None:
                # stack entries: ("class", name) | ("func", name)
                self.stack: list[tuple[str, str]] = []

            def _cur_class(self) -> str | None:
                for kind, name in reversed(self.stack):
                    if kind == "class":
                        return name
                return None

            def visit_ClassDef(self, node: ast.ClassDef) -> None:
                program.classes.setdefault(node.name, []).append(
                    (modkey, node)
                )
                self.stack.append(("class", node.name))
                self.generic_visit(node)
                self.stack.pop()

            def _func(self, node) -> None:
                is_method = bool(self.stack) and self.stack[-1][0] == "class"
                info = ProgFunc(
                    node=node,
                    relpath=relpath,
                    modkey=modkey,
                    cls=self._cur_class(),
                    name=getattr(node, "name", "<lambda>"),
                    lineno=node.lineno,
                    is_method=is_method,
                )
                program.funcs[id(node)] = info
                program._local.setdefault((modkey, info.name), []).append(info)
                if is_method:
                    program._methods.setdefault(
                        (info.cls, info.name), []
                    ).append(info)
                    program._by_method.setdefault(info.name, []).append(info)
                elif not any(k == "func" for k, _ in self.stack):
                    program._module_level.setdefault(
                        (modkey, info.name), []
                    ).append(info)
                self.stack.append(("func", info.name))
                self.generic_visit(node)
                self.stack.pop()

            visit_FunctionDef = _func
            visit_AsyncFunctionDef = _func

            def visit_Lambda(self, node: ast.Lambda) -> None:
                info = ProgFunc(
                    node=node,
                    relpath=relpath,
                    modkey=modkey,
                    cls=self._cur_class(),
                    name="<lambda>",
                    lineno=node.lineno,
                )
                program.funcs[id(node)] = info
                self.stack.append(("func", "<lambda>"))
                self.generic_visit(node)
                self.stack.pop()

        V().visit(tree)

    # ------------------------------------------------------------ resolution
    def info_for(self, node: ast.AST) -> ProgFunc | None:
        return self.funcs.get(id(node))

    def _class_init(self, cls_name: str) -> list[ProgFunc]:
        return self._methods.get((cls_name, "__init__"), [])

    def resolve_name(self, fn: ProgFunc, name: str) -> list[ProgFunc]:
        """A bare-name call inside ``fn``: local/module functions, then
        ``from``-imported symbols (functions or a class's __init__)."""
        local = [
            f
            for f in self._local.get((fn.modkey, name), [])
            if not f.is_method
        ]
        if local:
            return local
        alias = self._aliases.get(fn.modkey, {}).get(name)
        if alias is not None:
            if alias[0] == "symbol":
                _, mk, sym = alias
                hit = self._module_level.get((mk, sym), [])
                if hit:
                    return hit
                if sym in self.classes:
                    return self._class_init(sym)
        if name in self.classes:
            # class defined in this module (instantiation runs __init__)
            if any(mk == fn.modkey for mk, _ in self.classes[name]):
                return self._class_init(name)
        return []

    def resolve_call(
        self, fn: ProgFunc, call: ast.Call, *, unique_methods: bool = True
    ) -> tuple[list[ProgFunc], bool]:
        """Candidate callees for one call; second element = ambiguous
        (name-based obj.method with more than one candidate class).

        ``unique_methods=True`` (context propagation) drops ambiguous
        matches entirely; False (lock-graph may-acquire) keeps up to
        AMBIG_LIMIT candidates and reports the ambiguity."""
        f = call.func
        if isinstance(f, ast.Name):
            return self.resolve_name(fn, f.id), False
        if not isinstance(f, ast.Attribute):
            return [], False
        chain = dotted(f)
        if not chain:
            return [], False
        parts = chain.split(".")
        base, attr = parts[0], parts[-1]
        if base in ("self", "cls") and fn.cls is not None and len(parts) == 2:
            return self._methods.get((fn.cls, attr), []), False
        alias = self._aliases.get(fn.modkey, {}).get(base)
        if alias is not None and alias[0] == "module" and len(parts) == 2:
            mk = alias[1]
            hit = self._module_level.get((mk, attr), [])
            if hit:
                return hit, False
            if any(m == mk for m, _ in self.classes.get(attr, [])):
                return self._class_init(attr), False
        # plain obj.method: name-based, bounded
        cands = self._by_method.get(attr, [])
        classes = {c.cls for c in cands}
        if len(classes) == 1:
            return cands, False
        if unique_methods or len(classes) > AMBIG_LIMIT:
            return [], len(classes) > 1
        return cands, True

    def calls_in(self, fn: ProgFunc) -> list[ast.Call]:
        """Call nodes in fn's body, NOT descending into nested defs or
        lambdas (those are their own ProgFuncs with their own contexts)."""
        out: list[ast.Call] = []
        stack = list(ast.iter_child_nodes(fn.node))
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(node, ast.Call):
                out.append(node)
            stack.extend(ast.iter_child_nodes(node))
        return out

    # ------------------------------------------------------------ seeding
    def _import_neighborhood(self, modkey: str) -> set[str]:
        """The module itself plus every analyzed module it imports —
        the resolution horizon for liberal seed matching."""
        out = {modkey}
        for alias in self._aliases.get(modkey, {}).values():
            out.add(alias[1])
        return out

    def _seed_ref(self, fn: ProgFunc, expr: ast.expr, ctx: str) -> None:
        """Mark the function a callable REFERENCE points at. Seeds are
        liberal on purpose (a missed spawn seed silently blesses a racy
        function as single-context) but bounded by the spawner's import
        neighborhood: ``run_in_executor(ex, pm.engine.submit)`` must seed
        TpuEngine.submit without also smearing ``executor`` onto every
        ``submit`` method in the program — an over-wide seed propagates
        phantom contexts through whole subsystems. ``ctx=DEVICE_MESH``
        sets the separate mesh flag instead of a concurrency context."""

        def mark(h: ProgFunc) -> None:
            if ctx == DEVICE_MESH:
                h.mesh = True
            else:
                h.contexts.add(ctx)

        if isinstance(expr, ast.Lambda):
            info = self.info_for(expr)
            if info is not None:
                mark(info)
            return
        if isinstance(expr, ast.Name):
            hits = self.resolve_name(fn, expr.id)
            if not hits:
                near = self._import_neighborhood(fn.modkey)
                hits = [
                    f
                    for (mk, nm), fs in self._local.items()
                    if nm == expr.id and mk in near
                    for f in fs
                ]
            for h in hits:
                mark(h)
            return
        if isinstance(expr, ast.Attribute):
            chain = dotted(expr)
            parts = chain.split(".") if chain else []
            if (
                len(parts) == 2
                and parts[0] in ("self", "cls")
                and fn.cls is not None
            ):
                for h in self._methods.get((fn.cls, parts[1]), []):
                    mark(h)
                return
            near = self._import_neighborhood(fn.modkey)
            for h in self._by_method.get(expr.attr, []):
                if h.modkey in near:
                    mark(h)

    def _seed(self) -> None:
        for info in self.funcs.values():
            if isinstance(info.node, ast.AsyncFunctionDef):
                info.contexts.add(LOOP)
        # Thread subclasses: run() executes on the spawned thread
        for cls_name, defs in self.classes.items():
            for _mk, node in defs:
                if any("Thread" in dotted(b) for b in node.bases):
                    for m in self._methods.get((cls_name, "run"), []):
                        m.contexts.add(DAEMON)
        for info in list(self.funcs.values()):
            pool_fanout = False
            for call in self.calls_in(info):
                f = call.func
                name = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else ""
                )
                recv = dotted(f.value).lower() if isinstance(
                    f, ast.Attribute
                ) else ""
                if name in _EXECUTOR_SPAWNS:
                    # run_in_executor(ex, fn, ...) / to_thread(fn, ...)
                    idx = 1 if name == "run_in_executor" else 0
                    if len(call.args) > idx:
                        self._seed_ref(info, call.args[idx], EXECUTOR)
                elif name in _LOOP_CALLBACKS:
                    for a in call.args:
                        self._seed_ref(info, a, LOOP)
                elif name in _THREAD_CTORS:
                    for kw in call.keywords:
                        if kw.arg == "target":
                            self._seed_ref(info, kw.value, DAEMON)
                elif name == "finalize" or (
                    name == "register" and recv == "atexit"
                ):
                    if name == "finalize" and len(call.args) > 1:
                        self._seed_ref(info, call.args[1], FINALIZER)
                    elif name == "register" and call.args:
                        self._seed_ref(info, call.args[0], FINALIZER)
                elif name == "submit" and (
                    "pool" in recv or "ex" in recv.split(".")[-1]
                ):
                    if call.args:
                        self._seed_ref(info, call.args[0], POOL_WORKER)
                elif name == "run" and "pool" in recv:
                    pool_fanout = True
                    for a in call.args:
                        if isinstance(a, (ast.List, ast.Tuple)):
                            for el in a.elts:
                                self._seed_ref(info, el, POOL_WORKER)
                elif name in _MESH_SPAWNS:
                    # shard_map(fn, mesh=..., ...): fn (and everything it
                    # calls) is an SPMD device program over the mesh
                    if call.args:
                        self._seed_ref(info, call.args[0], DEVICE_MESH)
            if pool_fanout:
                # the engine builds its thunk lists (lambdas calling the
                # real shard bodies) before the pool.run call; every
                # lambda in a fan-out function runs on a pool worker
                for sub in ast.walk(info.node):
                    if isinstance(sub, ast.Lambda):
                        li = self.info_for(sub)
                        if li is not None:
                            li.contexts.add(POOL_WORKER)

    # ------------------------------------------------------------ fixpoint
    def _propagate(self) -> None:
        work = [f for f in self.funcs.values() if f.contexts]
        # monotone: a function re-enters the worklist only when its
        # context set grew
        while work:
            fn = work.pop()
            if LIFECYCLE.search(fn.name):
                continue  # lifecycle contexts don't flow to callees
            for call in self.calls_in(fn):
                callees, _amb = self.resolve_call(fn, call)
                for callee in callees:
                    if not fn.contexts <= callee.contexts:
                        callee.contexts |= fn.contexts
                        work.append(callee)
        self._propagate_mesh()

    def _propagate_mesh(self) -> None:
        """Separate monotone fixpoint for the device-mesh flag: a callee
        of a mesh-traced function is itself traced into the SPMD program
        (no lifecycle exemption — tracing has no startup phase)."""
        work = [f for f in self.funcs.values() if f.mesh]
        while work:
            fn = work.pop()
            for call in self.calls_in(fn):
                callees, _amb = self.resolve_call(fn, call)
                for callee in callees:
                    if not callee.mesh:
                        callee.mesh = True
                        work.append(callee)

    # ------------------------------------------------------------ queries
    def contexts_of(self, node: ast.AST) -> frozenset[str]:
        info = self.funcs.get(id(node))
        return frozenset(info.contexts) if info is not None else frozenset()

    def is_mesh(self, node: ast.AST) -> bool:
        info = self.funcs.get(id(node))
        return bool(info is not None and info.mesh)


def contexts_race(a: frozenset, b: frozenset) -> bool:
    """Can code in context set ``a`` run concurrently with code in ``b``?
    Distinct contexts always race; a shared context races itself only
    when it is pool-backed (executor / pool_worker)."""
    if not a or not b:
        return False
    if (a | b) - (a & b):
        # at least one context on one side the other doesn't share —
        # two distinct contexts are concurrent by construction
        if len(a | b) > 1:
            return True
    return bool((a & b) & SELF_RACING)
