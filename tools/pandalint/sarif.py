"""SARIF 2.1.0 output: findings as CI annotations.

The Static Analysis Results Interchange Format is what code hosts ingest
to render inline PR annotations; ``pandalint --format sarif`` emits one
run with the full rule catalog as ``tool.driver.rules`` and one result
per ACTIVE finding (suppressed findings ride along with a
``suppressions`` entry so the host shows them struck through, matching
the in-tree reasoned-pragma convention).

Kept deliberately minimal and deterministic (stable rule ordering, no
timestamps): the golden-file test diffs the whole document.
"""

from __future__ import annotations

from tools.pandalint.checkers import rule_catalog

_ENGINE_RULES = {
    "SUP001": "suppression pragma without a reason",
    "SUP002": "stale suppression: pragma matches no finding",
    "SYN001": "file fails to parse",
}


def _rule_index() -> dict[str, int]:
    rules = sorted(rule_catalog()) + sorted(_ENGINE_RULES)
    return {rule: i for i, rule in enumerate(rules)}


def _rules_array() -> list[dict]:
    cat = rule_catalog()
    out = []
    for rule in sorted(cat):
        checker, desc = cat[rule]
        out.append(
            {
                "id": rule,
                "shortDescription": {"text": desc},
                "properties": {"checker": checker},
            }
        )
    for rule in sorted(_ENGINE_RULES):
        out.append(
            {
                "id": rule,
                "shortDescription": {"text": _ENGINE_RULES[rule]},
                "properties": {"checker": "engine"},
            }
        )
    return out


def _result(finding, index: dict[str, int]) -> dict:
    res = {
        "ruleId": finding.rule,
        "ruleIndex": index.get(finding.rule, -1),
        "level": "error" if finding.rule == "SYN001" else "warning",
        "message": {"text": finding.message},
        "locations": [
            {
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": finding.path,
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {
                        "startLine": finding.line,
                        # SARIF columns are 1-based; Finding.col is 0-based
                        "startColumn": finding.col + 1,
                    },
                }
            }
        ],
        "partialFingerprints": {"pandalint/v1": finding.fingerprint()},
    }
    if finding.suppressed:
        res["suppressions"] = [
            {
                "kind": "inSource",
                "justification": finding.suppress_reason,
            }
        ]
    return res


def to_sarif(findings: list, *, include_suppressed: bool = True) -> dict:
    """findings: Finding objects (active first is NOT required; order is
    preserved as given — callers pass a deterministically sorted list)."""
    index = _rule_index()
    results = [
        _result(f, index)
        for f in findings
        if include_suppressed or not f.suppressed
    ]
    return {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
            "Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "pandalint",
                        "informationUri": "tools/pandalint/README.md",
                        "rules": _rules_array(),
                    }
                },
                "columnKind": "utf16CodeUnits",
                "results": results,
            }
        ],
    }
