"""Suppression pragmas.

Syntax, anchored to the line carrying the finding (or the first line of the
enclosing multi-line statement)::

    expr  # pandalint: disable=RCT101 -- why this is safe here
    expr  # pandalint: disable=RCT101,TSK301 -- one reason covers both

A whole file can opt out of specific rules (line 1-5 header comment)::

    # pandalint: disable-file=HPN211 -- numpy host twin, not traced

A reason string after ``--`` is REQUIRED: a disable without one does not
suppress anything and is itself reported as SUP001, so every silenced
finding carries its justification in the source.
"""

from __future__ import annotations

import io
import re
import tokenize
from dataclasses import dataclass

_PRAGMA = re.compile(
    r"#\s*pandalint:\s*(?P<kind>disable(?:-file)?)\s*=\s*"
    r"(?P<rules>[A-Z0-9*,\s]+?)\s*(?:--\s*(?P<reason>.*\S))?\s*$"
)

_FILE_HEADER_LINES = 5  # disable-file pragmas must appear near the top


@dataclass
class Pragma:
    line: int
    rules: tuple[str, ...]   # rule ids, or ("*",)
    reason: str              # "" when missing (malformed)
    file_level: bool

    def covers(self, rule: str) -> bool:
        return "*" in self.rules or rule in self.rules


class SuppressionTable:
    """Parsed pragmas for one file."""

    def __init__(self, source: str):
        self.line_pragmas: dict[int, Pragma] = {}
        self.file_pragmas: list[Pragma] = []
        self.malformed: list[Pragma] = []  # pragma without a reason
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            comments = [
                (tok.start[0], tok.string)
                for tok in tokens
                if tok.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            comments = [
                (i + 1, line[line.index("#"):])
                for i, line in enumerate(source.splitlines())
                if "#" in line
            ]
        for lineno, text in comments:
            m = _PRAGMA.search(text)
            if not m:
                continue
            rules = tuple(
                r.strip() for r in m.group("rules").split(",") if r.strip()
            )
            reason = (m.group("reason") or "").strip()
            file_level = m.group("kind") == "disable-file"
            pragma = Pragma(lineno, rules, reason, file_level)
            if not reason:
                self.malformed.append(pragma)
                continue
            if file_level:
                if lineno <= _FILE_HEADER_LINES:
                    self.file_pragmas.append(pragma)
                else:
                    self.malformed.append(pragma)
            else:
                self.line_pragmas[lineno] = pragma

    def lookup(self, rule: str, lines: tuple[int, ...]) -> Pragma | None:
        """First pragma covering `rule` on any of the candidate lines, else a
        file-level pragma, else None."""
        for ln in lines:
            p = self.line_pragmas.get(ln)
            if p is not None and p.covers(rule):
                return p
        for p in self.file_pragmas:
            if p.covers(rule):
                return p
        return None
