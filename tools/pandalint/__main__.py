import sys

from tools.pandalint.cli import main

sys.exit(main())
