"""Baseline files: ratchet the strict gate to *new* violations only.

``--write-baseline FILE`` records the fingerprint of every finding (active
and suppressed) in the current tree. A later ``--strict --baseline FILE``
run ignores findings whose fingerprint is recorded, so the gate fails only
on violations introduced since the baseline. Fingerprints hash the rule id,
file path and normalized source text — not line numbers — so edits above a
baselined finding don't break the ratchet.
"""

from __future__ import annotations

import json

from tools.pandalint.finding import Finding

_VERSION = 1


def write_baseline(path: str, findings: list[Finding]) -> None:
    doc = {
        "version": _VERSION,
        "findings": {
            f.fingerprint(): {
                "rule": f.rule,
                "path": f.path,
                "line": f.line,
                "suppressed": f.suppressed,
            }
            for f in findings
        },
    }
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")


def load_baseline(path: str) -> set[str]:
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("version") != _VERSION:
        raise ValueError(f"unsupported baseline version: {doc.get('version')!r}")
    return set(doc.get("findings", {}))
