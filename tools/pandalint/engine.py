"""Lint engine: walk files, parse, run checkers, apply suppressions."""

from __future__ import annotations

import ast
import os

from tools.pandalint.checkers import ALL_CHECKERS, FileContext
from tools.pandalint.config import Config
from tools.pandalint.finding import FileReport, Finding
from tools.pandalint.suppress import SuppressionTable

_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def iter_python_files(paths: list[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
            for f in sorted(files):
                if f.endswith(".py"):
                    out.append(os.path.join(root, f))
    return out


class LintEngine:
    def __init__(self, config: Config | None = None, rules: set[str] | None = None):
        self.config = config or Config()
        self.rules = rules  # None = all
        self.checkers = [cls() for cls in ALL_CHECKERS]

    # ------------------------------------------------------------ one file
    def lint_file(self, path: str, relpath: str | None = None) -> FileReport:
        rel = (relpath or path).replace(os.sep, "/")
        report = FileReport(path=rel)
        try:
            with open(path, encoding="utf-8", errors="replace") as fh:
                source = fh.read()
        except OSError as e:
            report.parse_error = str(e)
            report.findings.append(
                Finding("SYN001", rel, 1, 0, f"cannot read file: {e}", "engine")
            )
            return report
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            report.parse_error = str(e)
            report.findings.append(
                Finding(
                    "SYN001",
                    rel,
                    e.lineno or 1,
                    (e.offset or 1) - 1,
                    f"syntax error: {e.msg} (file cannot import on this "
                    f"interpreter)",
                    "engine",
                    source_line=(e.text or "").strip(),
                )
            )
            return report

        ctx = FileContext(relpath=rel, tree=tree, source=source)
        table = SuppressionTable(source)
        for pragma in table.malformed:
            report.findings.append(
                Finding(
                    "SUP001",
                    rel,
                    pragma.line,
                    0,
                    "pandalint pragma without a `-- reason` (or disable-file "
                    "below the file header): nothing is suppressed",
                    "engine",
                    source_line=ctx.line_text(pragma.line),
                )
            )

        for checker in self.checkers:
            if not self.config.checker_applies(checker.name, rel):
                continue
            for raw in checker.check(ctx):
                if self.rules is not None and raw.rule not in self.rules:
                    continue
                # a pragma may sit on the finding's line or on the first
                # line of the enclosing logical statement (one line up for
                # wrapped expressions)
                candidates = (raw.line, raw.line - 1)
                pragma = table.lookup(raw.rule, candidates)
                report.findings.append(
                    Finding(
                        raw.rule,
                        rel,
                        raw.line,
                        raw.col,
                        raw.message,
                        checker.name,
                        source_line=ctx.line_text(raw.line),
                        suppressed=pragma is not None,
                        suppress_reason=pragma.reason if pragma else "",
                    )
                )
        report.findings.sort(key=lambda f: (f.line, f.col, f.rule))
        return report

    # ------------------------------------------------------------ many files
    def lint_paths(self, paths: list[str], root: str | None = None) -> list[FileReport]:
        root = root or os.getcwd()
        reports = []
        for path in iter_python_files(paths):
            rel = os.path.relpath(path, root)
            if rel.startswith(".."):
                rel = path
            reports.append(self.lint_file(path, rel))
        return reports


def lint_paths(
    paths: list[str],
    config: Config | None = None,
    rules: set[str] | None = None,
    root: str | None = None,
) -> list[FileReport]:
    return LintEngine(config, rules).lint_paths(paths, root)
