"""Lint engine: walk files, parse, run checkers, apply suppressions.

Two checker phases since pandaraces:

1. **Per-file checkers** (reactor, hotpath, ...) see one parsed file.
   This phase is embarrassingly parallel (``jobs``) and content-cacheable
   (``cache_path``): a file whose bytes didn't change since the last run
   re-uses its recorded findings — the gate runs in every tier-1, so the
   steady-state cost is one hash per file.
2. **Program checkers** (races, deadlocks) see the WHOLE parsed program —
   affinity seeds in one file classify functions in another. They run
   once per invocation, in-process, after the per-file phase; their
   findings flow through the same per-file suppression tables.

After both phases, well-formed pragmas that matched **no** finding are
themselves reported (SUP002): a stale suppression is a claim about the
code that stopped being true. Stale detection only runs when the full
rule set is active (a ``--rules`` subset would make every other pragma
look stale).
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import tempfile
from dataclasses import dataclass, field

from tools.pandalint.affinity import Program
from tools.pandalint.checkers import ALL_CHECKERS, FileContext, rule_catalog
from tools.pandalint.config import Config
from tools.pandalint.finding import FileReport, Finding
from tools.pandalint.lockgraph import LockGraph
from tools.pandalint.suppress import SuppressionTable

_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}

# bump when a change invalidates cached per-file findings wholesale
_CACHE_FORMAT = 2


def iter_python_files(paths: list[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
            for f in sorted(files):
                if f.endswith(".py"):
                    out.append(os.path.join(root, f))
    return out


def default_jobs() -> int:
    return min(4, os.cpu_count() or 1)


def default_cache_path() -> str:
    """Per-checkout cache file under the USER's cache dir (the repo tree
    must not grow derived state the gate then has to ignore, and a
    world-writable /tmp path would let another local user pre-poison the
    gate's findings cache)."""
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    if base.startswith("~"):  # no resolvable home: per-uid tempdir
        base = os.path.join(
            tempfile.gettempdir(), f"pandalint-{os.getuid()}"
        )
    tag = hashlib.sha256(os.getcwd().encode()).hexdigest()[:12]
    return os.path.join(base, "pandalint", f"cache-{tag}.json")


@dataclass
class _FileState:
    """Everything the engine holds per file between phases."""

    path: str
    rel: str
    report: FileReport
    ctx: FileContext | None = None
    table: SuppressionTable | None = None
    source_hash: str = ""
    from_cache: bool = False
    file_findings: list[Finding] = field(default_factory=list)


# --------------------------------------------------------------- worker side
# Module-level so ProcessPoolExecutor (spawn) can import it; the worker
# re-runs only the per-file checkers and ships Finding dicts back.
_worker_engine: "LintEngine | None" = None


def _worker_init(config: Config, rules: set[str] | None) -> None:
    global _worker_engine
    _worker_engine = LintEngine(config, rules)


def _worker_lint(args: tuple[str, str]) -> tuple[str, list[dict], str | None]:
    path, rel = args
    assert _worker_engine is not None
    state = _worker_engine._parse(path, rel)
    if state.ctx is not None:
        _worker_engine._run_file_checkers(state)
    findings = [f.to_dict() for f in state.file_findings]
    return rel, findings, state.report.parse_error


def _finding_from_dict(d: dict) -> Finding:
    return Finding(
        d["rule"],
        d["path"],
        d["line"],
        d["col"],
        d["message"],
        d["checker"],
        source_line=d.get("source_line", ""),
        suppressed=d.get("suppressed", False),
        suppress_reason=d.get("suppress_reason", ""),
    )


class LintEngine:
    def __init__(
        self,
        config: Config | None = None,
        rules: set[str] | None = None,
        jobs: int = 1,
        cache_path: str | None = None,
    ):
        self.config = config or Config()
        self.rules = rules  # None = all
        self.jobs = max(1, int(jobs))
        self.cache_path = cache_path
        self.checkers = [cls() for cls in ALL_CHECKERS]
        self.file_checkers = [c for c in self.checkers if not c.program_level]
        self.program_checkers = [c for c in self.checkers if c.program_level]

    # ------------------------------------------------------------ plumbing
    def _salt(self) -> str:
        """Cache invalidation scope: engine format, rule set, config."""
        h = hashlib.sha256()
        h.update(str(_CACHE_FORMAT).encode())
        h.update(",".join(sorted(rule_catalog())).encode())
        h.update(str(sorted(self.rules)) .encode() if self.rules else b"all")
        h.update(self.config.package_root.encode())
        h.update(str(sorted(self.config.scopes.items())).encode())
        return h.hexdigest()

    def _parse(self, path: str, relpath: str | None = None) -> _FileState:
        rel = (relpath or path).replace(os.sep, "/")
        report = FileReport(path=rel)
        state = _FileState(path=path, rel=rel, report=report)
        try:
            with open(path, encoding="utf-8", errors="replace") as fh:
                source = fh.read()
        except OSError as e:
            report.parse_error = str(e)
            f = Finding("SYN001", rel, 1, 0, f"cannot read file: {e}", "engine")
            report.findings.append(f)
            state.file_findings.append(f)
            return state
        state.source_hash = hashlib.sha256(source.encode()).hexdigest()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            report.parse_error = str(e)
            f = Finding(
                "SYN001",
                rel,
                e.lineno or 1,
                (e.offset or 1) - 1,
                f"syntax error: {e.msg} (file cannot import on this "
                f"interpreter)",
                "engine",
                source_line=(e.text or "").strip(),
            )
            report.findings.append(f)
            state.file_findings.append(f)
            return state
        state.ctx = FileContext(relpath=rel, tree=tree, source=source)
        state.table = SuppressionTable(source)
        for pragma in state.table.malformed:
            report.findings.append(
                Finding(
                    "SUP001",
                    rel,
                    pragma.line,
                    0,
                    "pandalint pragma without a `-- reason` (or disable-file "
                    "below the file header): nothing is suppressed",
                    "engine",
                    source_line=state.ctx.line_text(pragma.line),
                )
            )
        return state

    def _make_finding(
        self, state: _FileState, raw, checker_name: str
    ) -> Finding:
        # a pragma may sit on the finding's line or on the first line of
        # the enclosing logical statement (one line up for wrapped exprs)
        pragma = state.table.lookup(raw.rule, (raw.line, raw.line - 1))
        return Finding(
            raw.rule,
            state.rel,
            raw.line,
            raw.col,
            raw.message,
            checker_name,
            source_line=state.ctx.line_text(raw.line),
            suppressed=pragma is not None,
            suppress_reason=pragma.reason if pragma else "",
        )

    def _run_file_checkers(self, state: _FileState) -> None:
        for checker in self.file_checkers:
            if not self.config.checker_applies(checker.name, state.rel):
                continue
            for raw in checker.check(state.ctx):
                if self.rules is not None and raw.rule not in self.rules:
                    continue
                f = self._make_finding(state, raw, checker.name)
                state.file_findings.append(f)
                state.report.findings.append(f)

    def _run_program_checkers(self, states: list[_FileState]) -> None:
        parsed = [s for s in states if s.ctx is not None]
        if not parsed:
            return
        by_rel = {s.rel: s for s in parsed}
        program = Program([(s.rel, s.ctx.tree) for s in parsed])
        locks = LockGraph(program)
        for checker in self.program_checkers:
            for rel, raw in checker.check_program(program, locks):
                state = by_rel.get(rel)
                if state is None:
                    continue
                if not self.config.checker_applies(checker.name, rel):
                    continue
                if self.rules is not None and raw.rule not in self.rules:
                    continue
                state.report.findings.append(
                    self._make_finding(state, raw, checker.name)
                )

    def _stale_pragmas(self, state: _FileState) -> None:
        """SUP002: a well-formed pragma that silenced nothing. Only
        meaningful when every rule ran (a --rules subset would make the
        other pragmas look stale), enforced by the caller."""
        if state.table is None or state.ctx is None:
            return
        used: set[int] = set()
        for f in state.report.findings:
            p = state.table.lookup(f.rule, (f.line, f.line - 1))
            if p is not None:
                used.add(id(p))
        pragmas = list(state.table.line_pragmas.values()) + list(
            state.table.file_pragmas
        )
        for p in pragmas:
            if id(p) in used:
                continue
            rules = ",".join(p.rules)
            state.report.findings.append(
                Finding(
                    "SUP002",
                    state.rel,
                    p.line,
                    0,
                    f"stale suppression: `disable={rules}` no longer "
                    f"matches any finding "
                    f"{'in this file' if p.file_level else 'on this line'} "
                    f"— the claim it documents stopped being true; remove "
                    f"the pragma (or fix the rule id)",
                    "engine",
                    source_line=state.ctx.line_text(p.line),
                )
            )

    def suppression_inventory(
        self, states: list[_FileState]
    ) -> list[dict]:
        out = []
        for state in states:
            if state.table is None:
                continue
            stale_lines = {
                f.line
                for f in state.report.findings
                if f.rule == "SUP002"
            }
            pragmas = list(state.table.line_pragmas.values()) + list(
                state.table.file_pragmas
            )
            for p in sorted(pragmas, key=lambda p: p.line):
                out.append(
                    {
                        "path": state.rel,
                        "line": p.line,
                        "rules": list(p.rules),
                        "reason": p.reason,
                        "file_level": p.file_level,
                        "stale": p.line in stale_lines,
                    }
                )
        return out

    # ------------------------------------------------------------ one file
    def lint_file(self, path: str, relpath: str | None = None) -> FileReport:
        state = self._parse(path, relpath)
        if state.ctx is not None:
            self._run_file_checkers(state)
            self._run_program_checkers([state])
            if self.rules is None:
                self._stale_pragmas(state)
        state.report.findings.sort(key=lambda f: (f.line, f.col, f.rule))
        return state.report

    # ------------------------------------------------------------ many files
    def lint_paths(
        self, paths: list[str], root: str | None = None
    ) -> list[FileReport]:
        reports, _states = self.lint_paths_with_states(paths, root)
        return reports

    def lint_paths_with_states(
        self, paths: list[str], root: str | None = None
    ) -> tuple[list[FileReport], list[_FileState]]:
        root = root or os.getcwd()
        states: list[_FileState] = []
        for path in iter_python_files(paths):
            rel = os.path.relpath(path, root)
            if rel.startswith(".."):
                rel = path
            # parse in-process always: the program phase needs every tree
            states.append(self._parse(path, rel))

        cache = self._load_cache()
        salt = self._salt()
        pending: list[_FileState] = []
        for state in states:
            if state.ctx is None:
                continue
            hit = cache.get(state.rel) if cache is not None else None
            if hit is not None and hit.get("hash") == state.source_hash:
                state.from_cache = True
                state.file_findings = [
                    _finding_from_dict(d) for d in hit["findings"]
                ]
                state.report.findings.extend(state.file_findings)
            else:
                pending.append(state)

        if self.jobs > 1 and len(pending) >= 8:
            self._run_parallel(pending)
        else:
            for state in pending:
                self._run_file_checkers(state)

        self._run_program_checkers(states)
        if self.rules is None:
            for state in states:
                self._stale_pragmas(state)
        for state in states:
            state.report.findings.sort(key=lambda f: (f.line, f.col, f.rule))
        self._store_cache(states, salt)
        return [s.report for s in states], states

    def _run_parallel(self, pending: list[_FileState]) -> None:
        """Per-file phase on a process pool. Fork when the process is
        still single-threaded (cheap workers, no re-import); spawn when
        threads exist — the gate runs inside pytest processes that own
        daemon threads (harvesters, fetch workers), and forking a
        threaded process can inherit held locks mid-critical-section.
        Any pool failure falls back to the serial path — parallelism is
        an optimization, never a correctness dependency."""
        import concurrent.futures as cf
        import multiprocessing as mp
        import threading

        method = (
            "fork"
            if "fork" in mp.get_all_start_methods()
            and threading.active_count() == 1
            else "spawn"
        )
        try:
            with cf.ProcessPoolExecutor(
                max_workers=min(self.jobs, len(pending)),
                mp_context=mp.get_context(method),
                initializer=_worker_init,
                initargs=(self.config, self.rules),
            ) as pool:
                by_rel = {s.rel: s for s in pending}
                for rel, findings, _err in pool.map(
                    _worker_lint,
                    [(s.path, s.rel) for s in pending],
                    chunksize=max(1, len(pending) // (self.jobs * 4)),
                ):
                    state = by_rel[rel]
                    state.file_findings = [
                        _finding_from_dict(d) for d in findings
                    ]
                    state.report.findings.extend(state.file_findings)
        except Exception:
            for state in pending:
                if not state.file_findings:
                    self._run_file_checkers(state)

    # ------------------------------------------------------------ cache
    # cache document: {"format": N, "salts": {salt: {rel: entry}}} — one
    # bucket per engine configuration, so alternating a --rules subset
    # with the full gate doesn't thrash the other's entries wholesale.
    _MAX_CACHE_SALTS = 4

    def _load_cache(self) -> dict | None:
        if not self.cache_path:
            return None
        try:
            with open(self.cache_path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return {}
        if doc.get("format") != _CACHE_FORMAT:
            return {}
        files = doc.get("salts", {}).get(self._salt())
        return files if isinstance(files, dict) else {}

    def _store_cache(self, states: list[_FileState], salt: str) -> None:
        if not self.cache_path:
            return
        files = {
            s.rel: {
                "hash": s.source_hash,
                "findings": [f.to_dict() for f in s.file_findings],
            }
            for s in states
            if s.ctx is not None and s.source_hash
        }
        try:
            try:
                with open(self.cache_path, encoding="utf-8") as fh:
                    doc = json.load(fh)
                if doc.get("format") != _CACHE_FORMAT:
                    doc = {}
            except (OSError, ValueError):
                doc = {}
            salts = doc.get("salts")
            if not isinstance(salts, dict):
                salts = {}
            bucket = salts.pop(salt, None)
            if not isinstance(bucket, dict):
                bucket = {}
            # MERGE into the bucket: a narrow spot-check run (one file)
            # must not evict the gate run's 160+ entries — stale entries
            # for edited files are harmless (their hash misses)
            bucket.update(files)
            salts[salt] = bucket  # re-insert last: insertion order = LRU
            while len(salts) > self._MAX_CACHE_SALTS:
                salts.pop(next(iter(salts)))
            doc = {"format": _CACHE_FORMAT, "salts": salts}
            cache_dir = os.path.dirname(self.cache_path) or "."
            os.makedirs(cache_dir, exist_ok=True)
            fd, tmp = tempfile.mkstemp(
                dir=cache_dir, prefix=".pandalint-cache-"
            )
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                json.dump(doc, fh)
            os.replace(tmp, self.cache_path)
        except OSError:
            pass  # cache is best-effort; the lint result stands


def lint_paths(
    paths: list[str],
    config: Config | None = None,
    rules: set[str] | None = None,
    root: str | None = None,
) -> list[FileReport]:
    return LintEngine(config, rules).lint_paths(paths, root)
