"""pandalint CLI.

Exit codes: 0 = gate passes, 1 = active findings under --strict (or parse
errors in any mode), 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tools.pandalint.baseline import load_baseline, write_baseline
from tools.pandalint.checkers import rule_catalog
from tools.pandalint.config import Config
from tools.pandalint.engine import LintEngine, default_cache_path, default_jobs


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pandalint",
        description="AST invariant checker: reactor stalls, TPU tracer "
        "leaks, lost tasks, iobuf copies, cross-context races, lock-order "
        "cycles.",
    )
    p.add_argument("paths", nargs="*", help="files or directories to lint")
    p.add_argument(
        "--strict",
        action="store_true",
        help="exit 1 on any active (non-suppressed, non-baselined) finding",
    )
    p.add_argument(
        "--format",
        choices=("text", "json", "sarif"),
        default="text",
        help="output format (default: text; sarif renders as CI annotations)",
    )
    p.add_argument(
        "--rules",
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--baseline",
        metavar="FILE",
        help="ignore findings whose fingerprint is recorded in FILE",
    )
    p.add_argument(
        "--write-baseline",
        metavar="FILE",
        help="record every current finding's fingerprint to FILE and exit 0",
    )
    p.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also print suppressed findings",
    )
    p.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule catalog and exit",
    )
    p.add_argument(
        "--list-suppressions",
        action="store_true",
        help="print every suppression pragma (with staleness) and exit",
    )
    p.add_argument(
        "--changed-only",
        nargs="?",
        const="__merge-base__",
        default=None,
        metavar="REF",
        help="report only findings in files changed since REF (default: "
        "the merge-base with main) plus untracked files; the whole tree "
        "is still analyzed — program-level rules need the full call "
        "graph and the content-hash cache keeps unchanged files cheap — "
        "but the gate and the output are scoped to the diff",
    )
    p.add_argument(
        "--jobs",
        type=int,
        default=default_jobs(),
        metavar="N",
        help="parallel per-file analysis workers (default: min(4, cpus); "
        "the whole-program phase always runs in-process)",
    )
    p.add_argument(
        "--cache-file",
        metavar="FILE",
        default=None,
        help="content-hash findings cache (default: a per-checkout file "
        "in the system temp dir)",
    )
    p.add_argument(
        "--no-cache",
        action="store_true",
        help="disable the findings cache for this run",
    )
    return p


def _changed_files(ref: str) -> set[str] | None:
    """Repo-root-relative posix paths changed since `ref` (diff + staged
    + untracked). None when git is unusable — the caller degrades to a
    usage error rather than silently linting nothing."""
    import subprocess

    def git(*cmd: str):
        try:
            return subprocess.run(
                ("git",) + cmd, capture_output=True, text=True, timeout=30
            )
        except (OSError, subprocess.TimeoutExpired):  # pragma: no cover
            return None

    if ref == "__merge-base__":
        base = None
        for upstream in ("main", "origin/main", "master"):
            mb = git("merge-base", "HEAD", upstream)
            if mb is not None and mb.returncode == 0:
                base = mb.stdout.strip()
                break
        if base is None:
            # detached/shallow checkout: diff against HEAD (uncommitted
            # work) is still the useful pre-commit scope
            base = "HEAD"
    else:
        base = ref
    diff = git("diff", "--name-only", base)
    if diff is None or diff.returncode != 0:
        return None
    changed = {ln.strip() for ln in diff.stdout.splitlines() if ln.strip()}
    untracked = git("ls-files", "--others", "--exclude-standard")
    if untracked is not None and untracked.returncode == 0:
        changed |= {
            ln.strip() for ln in untracked.stdout.splitlines() if ln.strip()
        }
    return changed


def main(argv: list[str] | None = None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        for rule, (checker, desc) in sorted(rule_catalog().items()):
            print(f"{rule}  [{checker}] {desc}")
        print("SUP001  [engine] suppression pragma without a reason")
        print("SUP002  [engine] stale suppression: pragma matches no finding")
        print("SYN001  [engine] file fails to parse")
        return 0

    if not args.paths:
        print("pandalint: no paths given (try: pandalint redpanda_tpu/)", file=sys.stderr)
        return 2

    for p in args.paths:
        if not os.path.exists(p):
            print(f"pandalint: path does not exist: {p}", file=sys.stderr)
            return 2

    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(rule_catalog()) - {"SUP001", "SUP002", "SYN001"}
        if unknown:
            print(f"pandalint: unknown rules: {', '.join(sorted(unknown))}", file=sys.stderr)
            return 2

    if args.jobs < 1:
        print("pandalint: --jobs must be >= 1", file=sys.stderr)
        return 2

    changed: set[str] | None = None
    if args.changed_only is not None:
        changed = _changed_files(args.changed_only)
        if changed is None:
            print(
                "pandalint: --changed-only needs a git checkout "
                f"(cannot diff against {args.changed_only!r})",
                file=sys.stderr,
            )
            return 2

    cache_path = None if args.no_cache else (
        args.cache_file or default_cache_path()
    )
    config = Config.load("pyproject.toml" if os.path.exists("pyproject.toml") else None)
    engine = LintEngine(config, rules, jobs=args.jobs, cache_path=cache_path)
    reports, states = engine.lint_paths_with_states(args.paths)

    if args.list_suppressions:
        inventory = engine.suppression_inventory(states)
        if rules is not None:
            # staleness derives from SUP002, which only runs with every
            # rule active — under a subset a pragma for any other rule
            # would LOOK stale; don't report a trustworthy-looking zero
            for s in inventory:
                s["stale"] = None
            print(
                "pandalint: staleness not evaluated under --rules "
                "(needs a full-rule run)",
                file=sys.stderr,
            )
        if args.format == "json":
            print(json.dumps(inventory, indent=2))
        else:
            for s in inventory:
                kind = "file" if s["file_level"] else "line"
                stale = "  [STALE]" if s["stale"] else ""
                print(
                    f"{s['path']}:{s['line']}: [{kind}] "
                    f"disable={','.join(s['rules'])} -- {s['reason']}{stale}"
                )
            if rules is None:
                n_stale = sum(1 for s in inventory if s["stale"])
                print(
                    f"pandalint: {len(inventory)} suppression(s), "
                    f"{n_stale} stale"
                )
            else:
                print(
                    f"pandalint: {len(inventory)} suppression(s), "
                    f"staleness unknown (--rules subset)"
                )
        return 0

    all_findings = [f for r in reports for f in r.findings]

    if args.write_baseline:
        write_baseline(args.write_baseline, all_findings)
        print(
            f"pandalint: wrote {len(all_findings)} fingerprint(s) to "
            f"{args.write_baseline}"
        )
        return 0

    baselined: set[str] = set()
    if args.baseline:
        try:
            baselined = load_baseline(args.baseline)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"pandalint: cannot load baseline: {e}", file=sys.stderr)
            return 2

    if changed is not None:
        # Scope the REPORT to the diff; the analysis above already ran
        # over everything so program-level rules saw the full call graph.
        import posixpath

        all_findings = [
            f
            for f in all_findings
            if posixpath.normpath(f.path) in changed
        ]

    active = [
        f
        for f in all_findings
        if not f.suppressed and f.fingerprint() not in baselined
    ]
    suppressed = [f for f in all_findings if f.suppressed]
    parse_errors = [f for f in all_findings if f.rule == "SYN001"]

    if args.format == "json":
        print(
            json.dumps(
                {
                    "files": len(reports),
                    "active": [f.to_dict() for f in active],
                    "suppressed": [f.to_dict() for f in suppressed],
                    "baselined": sorted(
                        f.fingerprint()
                        for f in all_findings
                        if not f.suppressed and f.fingerprint() in baselined
                    ),
                },
                indent=2,
            )
        )
    elif args.format == "sarif":
        from tools.pandalint.sarif import to_sarif

        print(json.dumps(to_sarif(active + suppressed), indent=2))
    else:
        for f in active:
            print(f.render())
        if args.show_suppressed:
            for f in suppressed:
                print(f.render())
        n_base = sum(
            1 for f in all_findings if not f.suppressed and f.fingerprint() in baselined
        )
        scope = (
            f" (changed-only: {len(changed)} changed path(s))"
            if changed is not None
            else ""
        )
        print(
            f"pandalint: {len(reports)} file(s), {len(active)} active, "
            f"{len(suppressed)} suppressed, {n_base} baselined{scope}"
        )

    if parse_errors:
        return 1
    if args.strict and active:
        return 1
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
