"""Whole-program resource-lifecycle + cancellation-safety analysis (RSL16xx).

The costliest bug class in this repo's history is lifecycle leaks on
exception/cancellation paths: PR 13's review rounds were almost entirely
hand-found admission-reservation leaks (a cancelled submit leaking its
admitted bytes forever, rpc InflightGate slots eaten by handler tasks
cancelled before their first step, a double-free on the abandonment
race). This checker makes the class mechanical, over the same affinity
call graph the race/deadlock rules use.

It models the repo's REAL resource vocabulary:

- ``MemoryAccount.try_acquire / acquire`` ↔ ``release`` (budget bytes)
- ``AdmissionController.try_admit / admit`` ↔ ``release``
- ``InflightGate.try_enter`` ↔ ``leave``
- ``Arena.acquire`` ↔ ``release`` — including the grown-by-replacement
  scratch contract from the PR-5 native framing: a buffer passed as the
  ``out=`` keyword may be REPLACED by the callee, in which case the
  call's bound result becomes an alias the caller must release too
- fetch-pool claim (``_free_workers.pop()``) ↔ rejoin (``append``)
- ``TpuEngine`` / ``HostStagePool`` construction ↔ ``shutdown``

and checks three rule families:

**RSL1601** — an acquired handle with a path to function exit (explicit
``return``/``raise``, or fall-through) that skips the paired release and
is not protected by ``try/finally`` or a with-adapter. The 1601 family
also flags the PR-13 double-free shape: one handle released through TWO
mechanisms (a direct/finally release AND a done-callback binding) — the
two race, and the fix is an atomic zero-swap.

**RSL1602** — cancellation leak in async code: a held handle crossing an
``await`` with no ``finally`` (or ``except BaseException``-and-reraise)
release discipline, or a held handle handed into a
``create_task``/``ensure_future`` coroutine with no
``add_done_callback`` that releases it — a task cancelled before its
first step never enters the coroutine body, so an in-coroutine
``finally`` cannot run (the exact PR-13 rpc-slot shape).

**RSL1603** — an owner object storing a ``TpuEngine``/``HostStagePool``
on ``self`` whose teardown methods (stop/shutdown/close) never reach the
resource's ``shutdown()`` along any resolved call path.

Recognized escape hatches (a handle stops being this function's
responsibility): returned or yielded, stored to an attribute/subscript,
appended into a collection, passed as a call argument (ownership
transfer), bound into a lambda default or closure (done-callback
discipline), or the refusal-guard branch (``if reserved == 0: return`` —
nothing was held). The analysis is lexical and path-insensitive on the
safe side: a release anywhere later in the function ends the hold, so
false positives stay near zero at the cost of missed loop-carried
shapes. Documented blind spots: handles referenced from nested defs /
lambdas are assumed managed by the closure, and a rebound handle name
ends tracking.

The module also exports the static acquire-site model
(:func:`model_sites`) that the runtime balance recorder
(``redpanda_tpu/coproc/leakwatch.py``) is validated against: the chaos
parity suite asserts every runtime-observed acquire site is a line of a
statement this model knows about.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Iterator

from tools.pandalint.affinity import Program, ProgFunc, dotted
from tools.pandalint.checkers.base import Checker, RawFinding
from tools.pandalint.lockgraph import LockGraph

# ------------------------------------------------------------ vocabulary
# receivers that are synchronization primitives, not payload resources —
# lock.acquire() is the lockgraph's domain, and the qdc is a latency
# controller whose acquire/release pair is unit-less
_LOCKISH = re.compile(r"lock|mutex|sem|cond|qdc", re.I)
_ARENA_RECV = re.compile(r"arena", re.I)
_POOL_RECV = re.compile(r"free_worker")

# helper releases resolve by NAME (`self._release(reserved)`): requiring
# body resolution would miss one-line forwarding helpers
_RELEASE_HELPER = re.compile(
    r"release|leave|rejoin|shutdown|close|teardown|cleanup|free"
)
_TEARDOWN_METHOD = re.compile(
    r"(^|_)(stop|shutdown|close|aclose|teardown)|__(a)?exit__"
)
_SPAWNS = {"create_task", "ensure_future"}


@dataclass(frozen=True)
class Kind:
    key: str
    releases: frozenset
    noun: str


KIND_ACCOUNT = Kind("account", frozenset({"release"}), "budget reservation")
KIND_ADMISSION = Kind(
    "admission", frozenset({"release"}), "admission reservation"
)
KIND_GATE = Kind("gate", frozenset({"leave"}), "inflight slot")
KIND_ARENA = Kind("arena", frozenset({"release"}), "arena buffer")
KIND_POOL = Kind("pool", frozenset({"append"}), "fetch-pool worker")
KIND_ENGINE = Kind(
    "engine", frozenset({"shutdown", "stop", "close"}), "engine/pool"
)

# owner-class constructors whose instances demand a teardown call
OWNER_CTORS = {"TpuEngine": KIND_ENGINE, "HostStagePool": KIND_ENGINE}
_OWNER_TEARDOWNS = ("shutdown", "stop", "close", "aclose")


def acquire_kind(call: ast.Call) -> Kind | None:
    """Classify one call node as a resource acquisition, or None."""
    f = call.func
    if isinstance(f, ast.Name):
        return KIND_ENGINE if f.id in OWNER_CTORS else None
    if not isinstance(f, ast.Attribute):
        return None
    attr = f.attr
    if attr in OWNER_CTORS:  # module-aliased ctor: host_pool.HostStagePool
        return KIND_ENGINE
    if attr == "try_enter":
        return KIND_GATE
    if attr in ("try_admit", "admit"):
        return KIND_ADMISSION
    recv = dotted(f.value)
    tail = recv.rsplit(".", 1)[-1] if recv else ""
    if attr == "pop":
        return KIND_POOL if _POOL_RECV.search(tail) else None
    if attr in ("acquire", "try_acquire"):
        if recv and _LOCKISH.search(recv):
            return None
        if _ARENA_RECV.search(tail):
            return KIND_ARENA
        return KIND_ACCOUNT
    return None


# ------------------------------------------------------------ events
@dataclass
class _Ev:
    """One lexical occurrence the per-site state machine interprets."""

    kind: str  # call|await|spawn|done_cb|lambda|closure|return|raise|
    #            rebind|store|alias|yield
    line: int
    col: int
    names: frozenset = frozenset()
    attr: str = ""
    recv: str = ""
    outnames: frozenset = frozenset()
    targets: frozenset = frozenset()
    guards: tuple = ()  # ((test_node, polarity), ...) innermost last
    tries: tuple = ()  # enclosing ast.Try nodes, innermost last


def _names_in(node) -> frozenset:
    if node is None:
        return frozenset()
    return frozenset(
        n.id for n in ast.walk(node) if isinstance(n, ast.Name)
    )


class _EventWalker:
    """Flattens one function body into lexical events. Nested defs and
    lambdas are NOT descended into (their Name references become one
    closure/lambda escape event — the documented blind spot)."""

    def __init__(self, fn_node) -> None:
        self.out: list[_Ev] = []
        for st in fn_node.body:
            self._stmt(st, (), ())
        self.out.sort(key=lambda e: (e.line, e.col))

    def _ev(self, kind, node, guards, tries, *, at_end=False, **kw) -> None:
        # at_end: sort the event AFTER the node's sub-expressions — a
        # `return await io(), handle` must see the await happen BEFORE
        # ownership transfers to the caller
        line = (getattr(node, "end_lineno", None) or node.lineno) if at_end else node.lineno
        col = (
            (getattr(node, "end_col_offset", None) or node.col_offset)
            if at_end
            else node.col_offset
        )
        self.out.append(
            _Ev(kind, line, col, guards=guards, tries=tries, **kw)
        )

    # ------------------------------------------------------------ statements
    def _block(self, stmts, guards, tries) -> None:
        for st in stmts:
            self._stmt(st, guards, tries)

    def _stmt(self, st, guards, tries) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._ev(
                "closure", st, guards, tries, names=_names_in(st)
            )
            return
        if isinstance(st, ast.ClassDef):
            return
        if isinstance(st, ast.If):
            self._expr(st.test, guards, tries)
            self._block(st.body, guards + ((st.test, True),), tries)
            self._block(st.orelse, guards + ((st.test, False),), tries)
            return
        if isinstance(st, ast.Try):
            inner = tries + (st,)
            self._block(st.body, guards, inner)
            for h in st.handlers:
                self._block(h.body, guards, inner)
            self._block(st.orelse, guards, inner)
            self._block(st.finalbody, guards, tries)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._expr(st.iter, guards, tries)
            self._block(st.body, guards, tries)
            self._block(st.orelse, guards, tries)
            return
        if isinstance(st, ast.While):
            self._expr(st.test, guards, tries)
            self._block(st.body, guards, tries)
            self._block(st.orelse, guards, tries)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._expr(item.context_expr, guards, tries)
            self._block(st.body, guards, tries)
            return
        if isinstance(st, ast.Return):
            self._expr(st.value, guards, tries)
            self._ev(
                "return",
                st,
                guards,
                tries,
                at_end=True,
                names=_names_in(st.value),
            )
            return
        if isinstance(st, ast.Raise):
            self._expr(st.exc, guards, tries)
            self._ev(
                "raise",
                st,
                guards,
                tries,
                at_end=True,
                names=_names_in(st.exc),
            )
            return
        if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._assign(st, guards, tries)
            return
        if isinstance(st, ast.Expr):
            self._expr(st.value, guards, tries)
            return
        # generic compound fallback (match statements etc.): walk nested
        # statement lists with the same context, scan loose expressions
        for _name, value in ast.iter_fields(st):
            if isinstance(value, list):
                stmts = [v for v in value if isinstance(v, ast.stmt)]
                if stmts:
                    self._block(stmts, guards, tries)
                    continue
                for v in value:
                    if isinstance(v, ast.expr):
                        self._expr(v, guards, tries)
            elif isinstance(value, ast.expr):
                self._expr(value, guards, tries)

    def _assign(self, st, guards, tries) -> None:
        value = getattr(st, "value", None)
        self._expr(value, guards, tries)
        targets = (
            st.targets
            if isinstance(st, ast.Assign)
            else [st.target]
        )
        name_targets = frozenset(
            t.id for t in targets if isinstance(t, ast.Name)
        )
        # grown-by-replacement: `dst, ... = lib.f(..., out=scratch)` makes
        # the bound result an ALIAS of the out= buffer
        call = value.value if isinstance(value, ast.Await) else value
        if isinstance(call, ast.Call):
            outnames = frozenset(
                n
                for kw in call.keywords
                if kw.arg == "out"
                for n in _names_in(kw.value)
            )
            if outnames:
                # the replacement buffer is the FIRST element of a tuple
                # binding (dst, off, ... = lib.f(..., out=scratch) — the
                # batch_codec framing contract); the rest are counts
                alias_targets = set(name_targets)
                for t in targets:
                    if (
                        isinstance(t, ast.Tuple)
                        and t.elts
                        and isinstance(t.elts[0], ast.Name)
                    ):
                        alias_targets.add(t.elts[0].id)
                self._ev(
                    "alias",
                    st,
                    guards,
                    tries,
                    names=outnames,
                    targets=frozenset(alias_targets),
                )
        if name_targets:
            self._ev("rebind", st, guards, tries, names=name_targets)
        for t in targets:
            if isinstance(t, (ast.Attribute, ast.Subscript)) or (
                isinstance(t, ast.Tuple)
                and any(
                    isinstance(e, (ast.Attribute, ast.Subscript))
                    for e in t.elts
                )
            ):
                self._ev(
                    "store", st, guards, tries, names=_names_in(value)
                )
                break

    # ------------------------------------------------------------ expressions
    def _expr(self, node, guards, tries) -> None:
        if node is None:
            return
        if isinstance(node, ast.Await):
            self._ev(
                "await",
                node,
                guards,
                tries,
                names=_names_in(node.value),
            )
            self._expr(node.value, guards, tries)
            return
        if isinstance(node, (ast.Yield, ast.YieldFrom)):
            self._ev(
                "yield", node, guards, tries, names=_names_in(node.value)
            )
            self._expr(node.value, guards, tries)
            return
        if isinstance(node, ast.Lambda):
            names = _names_in(node.body)
            for d in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None
            ]:
                names |= _names_in(d)
            self._ev("lambda", node, guards, tries, names=names)
            return
        if isinstance(node, ast.Call):
            self._call(node, guards, tries)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child, guards, tries)

    def _call(self, node: ast.Call, guards, tries) -> None:
        f = node.func
        attr = (
            f.attr
            if isinstance(f, ast.Attribute)
            else (f.id if isinstance(f, ast.Name) else "")
        )
        recv = dotted(f.value) if isinstance(f, ast.Attribute) else ""
        if (
            attr in _SPAWNS
            and node.args
            and isinstance(node.args[0], ast.Call)
        ):
            inner = node.args[0]
            names = frozenset(
                n for a in inner.args for n in _names_in(a)
            ) | frozenset(
                n for kw in inner.keywords for n in _names_in(kw.value)
            )
            self._ev("spawn", node, guards, tries, names=names)
            for a in inner.args:
                self._expr(a, guards, tries)
            return
        if attr == "add_done_callback":
            names = frozenset(
                n for a in node.args for n in _names_in(a)
            )
            self._ev("done_cb", node, guards, tries, names=names)
            for a in node.args:
                self._expr(a, guards, tries)
            return
        argnames = frozenset(
            n for a in node.args for n in _names_in(a)
        ) | frozenset(
            n
            for kw in node.keywords
            if kw.arg != "out"
            for n in _names_in(kw.value)
        )
        self._ev(
            "call",
            node,
            guards,
            tries,
            attr=attr,
            recv=recv,
            names=argnames,
        )
        if isinstance(f, ast.Attribute):
            self._expr(f.value, guards, tries)
        for a in node.args:
            self._expr(a, guards, tries)
        for kw in node.keywords:
            self._expr(kw.value, guards, tries)


# ------------------------------------------------------------ sites
@dataclass
class _Site:
    fn: ProgFunc
    kind: Kind
    handle: str
    recv: str  # dotted receiver of the acquiring call ("" for ctors)
    stmt: ast.stmt
    call: ast.Call
    aliases: set = field(default_factory=set)

    @property
    def line(self) -> int:
        return self.stmt.lineno

    @property
    def end_line(self) -> int:
        return getattr(self.stmt, "end_lineno", None) or self.stmt.lineno

    def matches(self, name: str) -> bool:
        return name == self.handle or name in self.aliases


def _unwrap_calls(expr) -> list:
    """The Call nodes an assignment RHS may produce a handle from —
    sees through Await and the conditional-acquire IfExp shape
    (``arena.acquire(...) if arena else None``)."""
    if isinstance(expr, ast.Await):
        return _unwrap_calls(expr.value)
    if isinstance(expr, ast.IfExp):
        return _unwrap_calls(expr.body) + _unwrap_calls(expr.orelse)
    if isinstance(expr, ast.Call):
        return [expr]
    return []


def _own_nodes(fn: ProgFunc) -> Iterator[ast.AST]:
    """Walk fn's body WITHOUT descending into nested defs/lambdas —
    those are their own ProgFuncs and judge their own sites."""
    stack = list(ast.iter_child_nodes(fn.node))
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _local_sites(fn: ProgFunc) -> list[_Site]:
    out: list[_Site] = []
    for node in _own_nodes(fn):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        handle = None
        if isinstance(target, ast.Name):
            handle = target.id
        elif (
            isinstance(target, ast.Tuple)
            and target.elts
            and isinstance(target.elts[0], ast.Name)
        ):
            # `reserved, retry_ms = ctrl.try_admit(n)` — the reservation
            # is the FIRST element by vocabulary contract
            handle = target.elts[0].id
        if handle is None:
            continue
        for call in _unwrap_calls(node.value):
            kind = acquire_kind(call)
            if kind is None:
                continue
            f = call.func
            recv = (
                dotted(f.value) if isinstance(f, ast.Attribute) else ""
            )
            out.append(_Site(fn, kind, handle, recv, node, call))
            break
    return out


def _owner_sites(fn: ProgFunc) -> list[tuple[str, ast.stmt, str]]:
    """(attr, stmt, ctor_name) for ``self.X = TpuEngine(...)`` shapes
    (the ctor may be nested in an IfExp)."""
    out = []
    for node in _own_nodes(fn):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        if not (
            isinstance(t, ast.Attribute)
            and isinstance(t.value, ast.Name)
            and t.value.id == "self"
        ):
            continue
        for sub in ast.walk(node.value):
            if isinstance(sub, ast.Call):
                f = sub.func
                name = (
                    f.attr
                    if isinstance(f, ast.Attribute)
                    else (f.id if isinstance(f, ast.Name) else "")
                )
                if name in OWNER_CTORS:
                    out.append((t.attr, node, name))
                    break
    return out


# ------------------------------------------------------------ guards
def _guard_is_refusal(test, polarity: bool, site: _Site) -> bool:
    """Does this branch imply the handle was REFUSED (0/None → nothing
    held)? Truthy polarity checks the AND-leaves of the test; falsy
    polarity only the bare test."""
    h = site.handle

    def leaves(t):
        if isinstance(t, ast.BoolOp) and isinstance(t.op, ast.And):
            for v in t.values:
                yield from leaves(v)
        else:
            yield t

    def is_name(n, name):
        return isinstance(n, ast.Name) and n.id == name

    if polarity:
        for leaf in leaves(test):
            if isinstance(leaf, ast.UnaryOp) and isinstance(
                leaf.op, ast.Not
            ):
                if is_name(leaf.operand, h):
                    return True
            if (
                isinstance(leaf, ast.Compare)
                and len(leaf.ops) == 1
                and is_name(leaf.left, h)
            ):
                op, right = leaf.ops[0], leaf.comparators[0]
                if isinstance(op, (ast.Eq, ast.Is)) and (
                    (
                        isinstance(right, ast.Constant)
                        and right.value in (0, None)
                    )
                ):
                    return True
        return False
    # else-branch: `if reserved:` / `if reserved is not None:` / `> 0`
    if is_name(test, h):
        return True
    if (
        isinstance(test, ast.Compare)
        and len(test.ops) == 1
        and is_name(test.left, h)
    ):
        op, right = test.ops[0], test.comparators[0]
        if isinstance(op, (ast.IsNot, ast.NotEq, ast.Gt)) and (
            isinstance(right, ast.Constant) and right.value in (0, None)
        ):
            return True
    return False


def _ev_refused(ev: _Ev, site: _Site) -> bool:
    return any(
        _guard_is_refusal(test, pol, site) for test, pol in ev.guards
    )


# ------------------------------------------------------------ release match
def _is_release(site: _Site, ev: _Ev) -> bool:
    if ev.kind != "call":
        return False
    named = any(site.matches(n) for n in ev.names)
    if named and (
        ev.attr in site.kind.releases
        or _RELEASE_HELPER.search(ev.attr)
    ):
        return True
    if ev.attr in site.kind.releases and ev.recv:
        if site.recv and ev.recv == site.recv:
            return True
        # engine handles release via their own receiver: eng.shutdown()
        base = ev.recv.split(".", 1)[0]
        if site.matches(base):
            return True
    return False


def _finally_releases(try_node: ast.Try, site: _Site) -> bool:
    return _block_releases(try_node.finalbody, site)


def _handler_releases(try_node: ast.Try, site: _Site) -> bool:
    """A handler catching BaseException (or bare) that releases — the
    `except BaseException: release; raise` cancellation discipline."""
    for h in try_node.handlers:
        names = set()
        if h.type is None:
            names.add("BaseException")
        else:
            for n in ast.walk(h.type):
                if isinstance(n, ast.Name):
                    names.add(n.id)
                elif isinstance(n, ast.Attribute):
                    names.add(n.attr)
        if "BaseException" not in names:
            continue
        if _block_releases(h.body, site):
            return True
    return False


def _block_releases(stmts, site: _Site) -> bool:
    for st in stmts:
        for node in ast.walk(st):
            if isinstance(node, ast.Call):
                f = node.func
                attr = (
                    f.attr
                    if isinstance(f, ast.Attribute)
                    else (f.id if isinstance(f, ast.Name) else "")
                )
                recv = (
                    dotted(f.value)
                    if isinstance(f, ast.Attribute)
                    else ""
                )
                argnames = frozenset(
                    n for a in node.args for n in _names_in(a)
                )
                ev = _Ev(
                    "call",
                    node.lineno,
                    node.col_offset,
                    names=argnames,
                    attr=attr,
                    recv=recv,
                )
                if _is_release(site, ev):
                    return True
    return False


def _protected(ev: _Ev, site: _Site, *, cancellation: bool) -> bool:
    """Is this exit/await event covered by an enclosing try whose
    finally (or BaseException handler, for cancellation) releases?"""
    for t in reversed(ev.tries):
        if _finally_releases(t, site):
            return True
        if cancellation and _handler_releases(t, site):
            return True
    return False


# ------------------------------------------------------------ the checker
class LifecycleChecker(Checker):
    name = "lifecycle"
    program_level = True
    rules = {
        "RSL1601": (
            "acquired resource with a path to function exit that skips "
            "the paired release (or releases twice through racing "
            "mechanisms)"
        ),
        "RSL1602": (
            "cancellation leak: resource held across an await (or handed "
            "to a spawned task) without finally/done-callback release "
            "discipline"
        ),
        "RSL1603": (
            "owner object stores an engine/pool whose teardown never "
            "reaches its shutdown() along any resolved call path"
        ),
    }

    def check_program(
        self, program: Program, locks: LockGraph
    ) -> Iterator[tuple[str, RawFinding]]:
        findings: list[tuple[str, RawFinding]] = []
        for fn in program.funcs.values():
            if isinstance(fn.node, ast.Lambda):
                continue
            sites = _local_sites(fn)
            if sites:
                events = _EventWalker(fn.node).out
                for site in sites:
                    findings.extend(self._judge_site(site, events))
            for attr, stmt, ctor in _owner_sites(fn):
                f = self._judge_owner(program, fn, attr, stmt, ctor)
                if f is not None:
                    findings.append(f)
        for item in sorted(
            findings, key=lambda kv: (kv[0], kv[1].line, kv[1].rule)
        ):
            yield item

    # ------------------------------------------------------------ RSL1601/02
    def _judge_site(
        self, site: _Site, events: list[_Ev]
    ) -> Iterator[tuple[str, RawFinding]]:
        fn = site.fn
        is_async = isinstance(fn.node, ast.AsyncFunctionDef)
        held = True
        callback_bound = False  # handle escaped into a done-callback
        spawn_pending: _Ev | None = None
        for ev in events:
            if ev.line <= site.end_line:
                continue  # before/within the acquiring statement
            if _ev_refused(ev, site):
                continue  # refusal-guard branch: nothing is held there
            if ev.kind == "alias" and any(
                site.matches(n) for n in ev.names
            ):
                # grown-by-replacement: the out= result is ours to release
                site.aliases |= set(ev.targets)
                continue
            if ev.kind == "done_cb" and any(
                site.matches(n) for n in ev.names
            ):
                spawn_pending = None
                callback_bound = True
                held = False
                continue
            if _is_release(site, ev):
                if not held and callback_bound:
                    # PR-13 double-free: finally/direct release RACES the
                    # done-callback release of the same handle
                    yield (
                        fn.relpath,
                        RawFinding(
                            "RSL1601",
                            ev.line,
                            ev.col,
                            f"{fn.qualname}() releases the "
                            f"{site.kind.noun} `{site.handle}` here AND "
                            f"through a done-callback (both run on the "
                            f"abandonment race — the PR-13 double-free); "
                            f"keep ONE mechanism, or guard with an "
                            f"atomic zero-swap of the held amount",
                        ),
                    )
                    return
                held = False
                continue
            if ev.kind in ("lambda", "closure") and any(
                site.matches(n) for n in ev.names
            ):
                # closure/callback discipline: the closure owns it now
                spawn_pending = None
                callback_bound = ev.kind == "lambda"
                held = False
                continue
            if not held:
                continue
            if ev.kind == "spawn" and any(
                site.matches(n) for n in ev.names
            ):
                spawn_pending = ev
                held = False
                continue
            if ev.kind in ("return", "yield") and any(
                site.matches(n) for n in ev.names
            ):
                held = False  # ownership moves to the caller/consumer
                continue
            if ev.kind == "store" and any(
                site.matches(n) for n in ev.names
            ):
                held = False  # published to an attribute/collection
                continue
            if ev.kind == "rebind" and site.matches(
                tuple(ev.names)[0] if len(ev.names) == 1 else ""
            ):
                held = False  # handle name rebound: tracking ends
                continue
            if ev.kind == "call" and any(
                site.matches(n) for n in ev.names
            ):
                held = False  # ownership transfer to the callee
                continue
            if ev.kind == "await" and is_async:
                if any(site.matches(n) for n in ev.names):
                    held = False  # handle passed INTO the awaited call
                    continue
                if not _protected(ev, site, cancellation=True):
                    yield (
                        fn.relpath,
                        RawFinding(
                            "RSL1602",
                            site.line,
                            site.stmt.col_offset,
                            f"{fn.qualname}() holds the "
                            f"{site.kind.noun} `{site.handle}` across "
                            f"the await at line {ev.line} with no "
                            f"finally (or except-BaseException-and-"
                            f"reraise) release: a cancellation there "
                            f"leaks it forever — wrap the awaited "
                            f"region in try/finally releasing "
                            f"`{site.handle}`",
                        ),
                    )
                    return
                continue
            if ev.kind in ("return", "raise"):
                if _protected(ev, site, cancellation=False):
                    continue
                yield (
                    fn.relpath,
                    RawFinding(
                        "RSL1601",
                        site.line,
                        site.stmt.col_offset,
                        f"{fn.qualname}() acquires the "
                        f"{site.kind.noun} `{site.handle}` but the "
                        f"{ev.kind} at line {ev.line} exits without "
                        f"the paired "
                        f"{'/'.join(sorted(site.kind.releases))} — "
                        f"release in a finally, or guard the exit on "
                        f"the refusal value",
                    ),
                )
                return
        if spawn_pending is not None:
            yield (
                fn.relpath,
                RawFinding(
                    "RSL1602",
                    site.line,
                    site.stmt.col_offset,
                    f"{fn.qualname}() hands the {site.kind.noun} "
                    f"`{site.handle}` to the task spawned at line "
                    f"{spawn_pending.line} with no add_done_callback "
                    f"releasing it: a task cancelled before its first "
                    f"step never enters the coroutine body, so an "
                    f"in-coroutine finally leaks the "
                    f"{site.kind.noun} (the PR-13 rpc-slot shape) — "
                    f"release via t.add_done_callback(lambda _t, "
                    f"r={site.handle}: ...)",
                ),
            )
            return
        if held:
            yield (
                fn.relpath,
                RawFinding(
                    "RSL1601",
                    site.line,
                    site.stmt.col_offset,
                    f"{fn.qualname}() acquires the {site.kind.noun} "
                    f"`{site.handle}` and never releases, returns, or "
                    f"hands it off on any path — every acquisition "
                    f"needs a paired "
                    f"{'/'.join(sorted(site.kind.releases))}",
                ),
            )

    # ------------------------------------------------------------ RSL1603
    def _judge_owner(
        self,
        program: Program,
        fn: ProgFunc,
        attr: str,
        stmt: ast.stmt,
        ctor: str,
    ) -> tuple[str, RawFinding] | None:
        if fn.cls is None:
            return None
        methods = [
            m
            for (cls, _name), fns in program._methods.items()
            if cls == fn.cls
            for m in fns
            if m.modkey == fn.modkey
        ]
        teardowns = [
            m for m in methods if _TEARDOWN_METHOD.search(m.name)
        ]
        reached = False
        seen: set[int] = set()
        frontier = list(teardowns)
        for _depth in range(4):
            if reached or not frontier:
                break
            nxt: list[ProgFunc] = []
            for m in frontier:
                if id(m.node) in seen:
                    continue
                seen.add(id(m.node))
                if self._reaches_teardown(m, attr):
                    reached = True
                    break
                for call in program.calls_in(m):
                    callees, _amb = program.resolve_call(
                        m, call, unique_methods=False
                    )
                    nxt.extend(callees)
            frontier = nxt
        if teardowns and reached:
            return None
        why = (
            f"defines no stop/shutdown/close method at all"
            if not teardowns
            else f"has teardown methods "
            f"({', '.join(sorted(m.name for m in teardowns))}) but none "
            f"reaches self.{attr}.shutdown() along any resolved call "
            f"path"
        )
        return (
            fn.relpath,
            RawFinding(
                "RSL1603",
                stmt.lineno,
                stmt.col_offset,
                f"{fn.cls} stores a {ctor} in self.{attr} but {why} — "
                f"a daemon harvester/pool pins the whole engine for the "
                f"process lifetime; tear it down from the owner's "
                f"stop/shutdown",
            ),
        )

    @staticmethod
    def _reaches_teardown(m: ProgFunc, attr: str) -> bool:
        want = {f"self.{attr}.{t}" for t in _OWNER_TEARDOWNS}
        for node in ast.walk(m.node):
            if isinstance(node, ast.Attribute) and dotted(node) in want:
                return True
        return False


# ------------------------------------------------------------ runtime model
def model_sites(
    modules: list[tuple[str, ast.Module]],
) -> dict[str, set[int]]:
    """The static acquire-site model the leakwatch runtime recorder is
    validated against: relpath -> every line of every statement that
    performs a vocabulary acquisition (bound or not — the runtime
    attributes a wrapped call to its caller's current line, which is
    always within the acquiring statement)."""
    out: dict[str, set[int]] = {}

    def scan_stmt(relpath: str, st: ast.stmt) -> None:
        for node in ast.walk(st):
            if isinstance(node, ast.Call) and acquire_kind(node):
                end = getattr(st, "end_lineno", None) or st.lineno
                out.setdefault(relpath, set()).update(
                    range(st.lineno, end + 1)
                )
                return

    for relpath, tree in modules:
        for node in ast.walk(tree):
            for field_ in ("body", "orelse", "finalbody"):
                val = getattr(node, field_, None)
                if not isinstance(val, list):  # Lambda.body is an expr
                    continue
                for st in val:
                    if isinstance(st, ast.stmt):
                        scan_stmt(relpath, st)
            for h in getattr(node, "handlers", []) or []:
                for st in h.body:
                    scan_stmt(relpath, st)
    return out
