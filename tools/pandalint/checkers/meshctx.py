"""Device-mesh purity (MSH13xx): shard_map-traced functions stay device-only.

A function handed to ``shard_map(fn, mesh=...)`` (and everything it calls
— the affinity ``device_mesh`` flag propagates over resolved calls) is an
SPMD program: its body runs under jax tracing once and then replicates
onto every mesh device. Host-only work inside it is a defect twice over:

- a **host API call** (``time.perf_counter``, ``np.asarray``, ``open``,
  a lock acquire) executes at TRACE time, not per launch — it silently
  burns into the compiled program as a constant, or worse, performs a
  side effect once on the tracing thread that the author believed ran
  per device per tick (the hot-path impurity HPS2xx flags for jit
  functions, extended here to the mesh context);
- a **host state write** (``self.x = ...``, ``global``) from inside a
  traced body mutates engine state from what LOOKS like device code —
  the one shape the executor-affinity race analysis cannot see, because
  the mesh context deliberately does not participate in it
  (affinity.DEVICE_MESH docs).

Rules fire at the offending line inside the mesh-traced function.
Host-module detection is import-table based: a call whose receiver chain
roots at an alias of numpy/time/os/threading/... (or a bare ``open`` /
``print``) is host work. jax/jnp and arithmetic stay silent.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.pandalint.affinity import Program, ProgFunc
from tools.pandalint.checkers.base import Checker, RawFinding, dotted

# top-level modules whose calls are host work under tracing
HOST_MODULES = {
    "numpy", "time", "os", "threading", "queue", "socket", "subprocess",
    "logging", "random", "struct", "io", "ctypes", "json", "asyncio",
}
HOST_BUILTINS = {"open", "print", "input"}


def _import_aliases(tree: ast.Module) -> dict[str, str]:
    """name -> top-level module, over EVERY import in the file (function-
    level imports included — the engine imports jax/numpy inside legs)."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                top = a.name.split(".")[0]
                out[a.asname or top] = top
        elif isinstance(node, ast.ImportFrom) and node.module:
            top = node.module.split(".")[0]
            for a in node.names:
                out[a.asname or a.name] = top
    return out


class MeshCtxChecker(Checker):
    name = "meshctx"
    program_level = True
    rules = {
        "MSH1301": (
            "mesh-traced function calls a host-only API: the call runs "
            "once at trace time (not per device per launch) and breaks "
            "SPMD purity"
        ),
        "MSH1302": (
            "mesh-traced function mutates host state (attribute/global "
            "write) from inside the traced SPMD body"
        ),
    }

    def check_program(
        self, program: Program, locks
    ) -> Iterator[tuple[str, RawFinding]]:
        aliases: dict[str, dict[str, str]] = {
            rel: _import_aliases(tree) for rel, tree in program.modules
        }
        findings: list[tuple[str, RawFinding]] = []
        for fn in program.funcs.values():
            if not fn.mesh:
                continue
            findings.extend(self._check_fn(fn, aliases.get(fn.relpath, {})))
        for item in sorted(findings, key=lambda kv: (kv[0], kv[1].line)):
            yield item

    def _check_fn(
        self, fn: ProgFunc, aliases: dict[str, str]
    ) -> Iterator[tuple[str, RawFinding]]:
        stack = list(ast.iter_child_nodes(fn.node))
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue  # nested defs carry their own mesh flag
            if isinstance(node, ast.Call):
                chain = dotted(node.func)
                base = chain.split(".")[0] if chain else ""
                mod = aliases.get(base)
                if base in HOST_BUILTINS and base not in aliases:
                    yield (
                        fn.relpath,
                        RawFinding(
                            "MSH1301",
                            node.lineno,
                            node.col_offset,
                            f"{fn.qualname}() is shard_map-traced "
                            f"(device_mesh context) but calls host builtin "
                            f"{base}() — host effects run once at trace "
                            f"time, not per device; move the call outside "
                            f"the traced body",
                        ),
                    )
                elif mod in HOST_MODULES:
                    yield (
                        fn.relpath,
                        RawFinding(
                            "MSH1301",
                            node.lineno,
                            node.col_offset,
                            f"{fn.qualname}() is shard_map-traced "
                            f"(device_mesh context) but calls {chain}() "
                            f"from host module '{mod}' — prepare the value "
                            f"on the host BEFORE tracing (the "
                            f"_prepare_cmp_consts pattern) or use the jnp "
                            f"equivalent",
                        ),
                    )
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if isinstance(t, ast.Attribute):
                        yield (
                            fn.relpath,
                            RawFinding(
                                "MSH1302",
                                t.lineno,
                                t.col_offset,
                                f"{fn.qualname}() is shard_map-traced but "
                                f"writes {dotted(t)} — host state mutated "
                                f"from inside the traced SPMD body (runs "
                                f"once at trace time and is invisible to "
                                f"the race analysis); hoist the write out "
                                f"of the mesh program",
                            ),
                        )
            elif isinstance(node, ast.Global):
                yield (
                    fn.relpath,
                    RawFinding(
                        "MSH1302",
                        node.lineno,
                        node.col_offset,
                        f"{fn.qualname}() is shard_map-traced but declares "
                        f"`global {', '.join(node.names)}` — host state "
                        f"mutation from the traced SPMD body",
                    ),
                )
            stack.extend(ast.iter_child_nodes(node))
