"""Metrics hygiene: hot paths must not look series up by name literal.

Every latency histogram and counter the SLO/trend planes judge is
single-sourced in ``redpanda_tpu/observability/probes.py`` (PR-2's
dispatch-layer contract): one module owns each series name, hot paths
import the binding. An ad-hoc ``registry.histogram("kafka_produce_…")``
inline in a hot function re-states the name as a string literal — and the
second spelling is where drift starts. PR-14's slodiff caught exactly this
shape at runtime (an SLO objective judging ``explode``, a lane the engine
no longer ran); this checker makes it static.

Heuristic scope (no type inference), confined to the hot-path packages
(``redpanda_tpu/{coproc,kafka,rpc,raft,storage}``) — probes.py itself and
the observability plane own their registrations and are outside the scope:

- MET1701: ``registry.histogram("literal", …)`` / ``registry.counter(
  "literal", …)`` INSIDE a function body — a per-call name-literal lookup
  in hot code. Module-level ``x = registry.counter("…")`` bind-once is the
  sanctioned idiom and does not count; neither does a lookup whose name is
  a variable (the binding owns the literal elsewhere).
- MET1702: the same lookup shape with a CONSTRUCTED name (f-string,
  concatenation, %-format, ``.format``/``join`` call) anywhere in the
  file — a name no grep or static tool can pin, so drift there is
  undetectable until a dashboard goes flat.

A deliberate lazy check-then-create (memoized per-label-set counters à la
``governor._decision_counter``) carries a reasoned
``# pandalint: disable=MET1701 -- …`` pragma, which doubles as the
documentation of why the per-call lookup is actually once-per-key.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.pandalint.checkers.base import (
    Checker,
    FileContext,
    RawFinding,
    dotted,
)

_LOOKUP_ATTRS = frozenset({"histogram", "counter"})

# name-argument shapes that CONSTRUCT the series name at the call site
_CONSTRUCTED = (ast.JoinedStr, ast.BinOp, ast.Call)


def _registry_lookup(call: ast.Call) -> str | None:
    """'histogram'|'counter' when this call is a registry series lookup."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr in _LOOKUP_ATTRS):
        return None
    recv = dotted(f.value)
    if recv == "registry" or recv.endswith(".registry"):
        return f.attr
    return None


def _name_arg(call: ast.Call) -> ast.expr | None:
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "name":
            return kw.value
    return None


class MetricsHygieneChecker(Checker):
    name = "metrics-hygiene"
    rules = {
        "MET1701": "per-call registry.histogram()/counter() name-literal "
                   "lookup in a hot path — bind the series once at module "
                   "level or in observability/probes.py and import it",
        "MET1702": "registry series lookup with a CONSTRUCTED name "
                   "(f-string/concat/format) — undetectable name drift",
    }

    def check(self, ctx: FileContext) -> Iterator[RawFinding]:
        # module-level statements: bind-once is the idiom; only flag
        # constructed names there (MET1702 applies everywhere)
        yield from self._walk(ctx.tree.body, in_function=False)

    def _walk(self, body, in_function: bool) -> Iterator[RawFinding]:
        for node in body:
            yield from self._visit(node, in_function)

    def _visit(self, node: ast.AST, in_function: bool) -> Iterator[RawFinding]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            inner = node.body if isinstance(node.body, list) else [node.body]
            yield from self._walk(inner, in_function=True)
            return
        if isinstance(node, ast.Call):
            kind = _registry_lookup(node)
            if kind is not None:
                arg = _name_arg(node)
                if isinstance(arg, _CONSTRUCTED):
                    yield RawFinding(
                        "MET1702",
                        node.lineno,
                        node.col_offset,
                        f"registry.{kind}() with a constructed series name "
                        f"— no grep can pin this spelling against "
                        f"probes.py; pass the literal through a named "
                        f"binding instead",
                    )
                elif in_function and isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    yield RawFinding(
                        "MET1701",
                        node.lineno,
                        node.col_offset,
                        f"registry.{kind}({arg.value!r}) looked up by name "
                        f"literal inside a hot-path function — bind the "
                        f"series once (module level or observability/"
                        f"probes.py) and import the binding; duplicated "
                        f"name literals are where series drift starts",
                    )
        for child in ast.iter_child_nodes(node):
            yield from self._visit(child, in_function)
