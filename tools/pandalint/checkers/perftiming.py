"""Raw pair-timing discipline: hot-path durations go through the probes.

pandapulse (observability/pulse.py) turns the engine's stage timers into
per-launch timelines BY CONSTRUCTION: every duration that flows through
``_stat_add``/``_stat_stage``/``tracer.record``/``probes.record_us`` lands
in /metrics AND (when tracing) in the flight recorder, so the timeline's
per-stage sums equal the ``stats()`` splits. A raw
``time.perf_counter()``/``time.monotonic()`` pair in a hot-path package
whose delta is logged, stored or dropped WITHOUT reaching one of those
sinks is a stage the recorder silently misses — the measurement exists,
but no timeline, no histogram and no SLO objective will ever see it.

Heuristic scope: the hot-path packages (``redpanda_tpu/coproc``,
``kafka``, ``rpc``, ``raft`` — see config.DEFAULT_SCOPES). Per-function
analysis, no type inference:

- PRF1501: a pair-timing delta (``clock() - t0`` / ``t1 - t0`` where the
  operands came from a raw clock) that never reaches a timing sink in the
  function. Routed shapes are exempt: the delta (or the variable it was
  assigned to) passed to a call whose dotted name mentions a sink token
  (``_stat`` / ``record`` / ``observe`` / ``journal`` / ``probe`` /
  ``pulse`` / ``hist``...), RETURNED/YIELDED (the caller owns routing),
  or used only in comparisons (deadline/timeout control flow is
  arithmetic, not measurement).
- PRF1502: clock MIXING — a delta whose start came from ``monotonic``
  and whose end from ``perf_counter`` (or vice versa). The two clocks
  share no epoch; the delta is garbage on every platform, always a bug.

A site that is genuinely not a measurement (or measures something the
probes deliberately must not see) carries a reasoned
``# pandalint: disable=PRF1501 -- ...`` pragma.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.pandalint.checkers.base import (
    Checker,
    FileContext,
    RawFinding,
    dotted,
)

# a call whose dotted name ends in one of these reads a raw clock
_CLOCKS = {
    "perf_counter": "perf",
    "perf_counter_ns": "perf",
    "monotonic": "mono",
    "monotonic_ns": "mono",
}

# a call whose dotted name mentions one of these consumes timings into
# the probes/trace/pulse plane (or an explicitly-timing-shaped sink)
_SINK_TOKENS = (
    "_stat", "stat_add", "stat_stage", "record", "observe", "journal",
    "probe", "pulse", "hist", "metric", "latency", "timing", "span",
    "note_launch", "elapsed",
)


def _clock_kind(node: ast.expr) -> str | None:
    """'perf'/'mono' when node is a raw clock call, else None."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted(node.func)
    leaf = name.rsplit(".", 1)[-1] if name else ""
    return _CLOCKS.get(leaf)


def _is_sink_call(call: ast.Call) -> bool:
    name = dotted(call.func).lower()
    return bool(name) and any(tok in name for tok in _SINK_TOKENS)


class _FunctionScope(ast.NodeVisitor):
    """One function's (or the module body's) pair-timing analysis. Nested
    defs/lambdas get their own scope — a closure's delta routes (or
    doesn't) in the frame that computes it."""

    def __init__(self) -> None:
        self.clock_vars: dict[str, str] = {}   # var -> 'perf' | 'mono'
        # delta expr id -> (node, kinds) candidates found in pass 1
        self.deltas: list[tuple[ast.BinOp, set[str]]] = []
        # var -> EVERY delta node whose value flowed into it (a var
        # reassigned from two different timers carries both)
        self.delta_vars: dict[str, set[int]] = {}
        self._by_id: dict[int, ast.BinOp] = {}
        self.routed: set[int] = set()          # id(delta node)
        self.mixed: list[ast.BinOp] = []

    # -------------------------------------------------------- pass 1
    def collect(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # a nested def is its own scope (see check())
            self._collect_stmt(stmt)

    def _iter_own(self, node: ast.AST):
        """Children of ``node`` excluding nested function/lambda bodies."""
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            yield child
            yield from self._iter_own(child)

    def _collect_stmt(self, stmt: ast.stmt) -> None:
        for node in [stmt, *self._iter_own(stmt)]:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    kind = _clock_kind(node.value)
                    if kind is not None:
                        self.clock_vars[tgt.id] = kind
                        continue
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
                kinds = set()
                for side in (node.left, node.right):
                    k = _clock_kind(side)
                    if k is None and isinstance(side, ast.Name):
                        k = self.clock_vars.get(side.id)
                    if k is not None:
                        kinds.add(k)
                    else:
                        kinds.clear()
                        break
                if kinds:
                    self.deltas.append((node, kinds))

    # -------------------------------------------------------- pass 2
    def analyze(self, body: list[ast.stmt]) -> None:
        body = [
            s for s in body
            if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        delta_ids = {id(n) for n, _ in self.deltas}
        self._by_id = {id(n): n for n, _ in self.deltas}
        # delta-ness propagates over assignments to fixpoint:
        # ``t = min(t, clock() - t0)`` makes ``t`` carry the delta,
        # ``speedup = a / b`` inherits EVERY delta flowing into either
        # operand — so routing only has to see the FINAL variable reach a
        # sink / return / comparison.
        changed = True
        while changed:
            changed = False
            for stmt in body:
                for node in [stmt, *self._iter_own(stmt)]:
                    if not (
                        isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                    ):
                        continue
                    tgt = node.targets[0].id
                    carried = self.delta_vars.get(tgt, set())
                    before = len(carried)
                    for sub in [node.value, *self._iter_own(node.value)]:
                        if id(sub) in delta_ids:
                            carried = carried | {id(sub)}
                        elif (
                            isinstance(sub, ast.Name)
                            and sub.id in self.delta_vars
                        ):
                            carried = carried | self.delta_vars[sub.id]
                    if len(carried) > before:
                        self.delta_vars[tgt] = carried
                        changed = True
        for stmt in body:
            self._route_stmt(stmt, delta_ids)

    def _routed_names_and_nodes(self, kids, delta_ids: set[int]) -> None:
        for kid in kids:
            for sub in [kid, *self._iter_own(kid)]:
                if id(sub) in delta_ids:
                    self.routed.add(id(sub))
                elif isinstance(sub, ast.Name) and sub.id in self.delta_vars:
                    self.routed.update(self.delta_vars[sub.id])

    def _route_stmt(self, stmt: ast.stmt, delta_ids: set[int]) -> None:
        for node in [stmt, *self._iter_own(stmt)]:
            routed_kids: list[ast.AST] = []
            if isinstance(node, ast.Call) and _is_sink_call(node):
                routed_kids = [*node.args, *(kw.value for kw in node.keywords)]
            elif isinstance(node, (ast.Return, ast.Yield, ast.Compare)):
                routed_kids = list(ast.iter_child_nodes(node))
            elif isinstance(node, (ast.If, ast.While)):
                routed_kids = [node.test]
            if routed_kids:
                self._routed_names_and_nodes(routed_kids, delta_ids)

    # -------------------------------------------------------- verdicts
    def findings(self) -> Iterator[RawFinding]:
        for node, kinds in self.deltas:
            if len(kinds) > 1:
                yield RawFinding(
                    "PRF1502",
                    node.lineno,
                    node.col_offset,
                    "pair-timing mixes monotonic and perf_counter: the "
                    "clocks share no epoch, so this delta is meaningless "
                    "— take both samples from ONE clock",
                )
                continue
            if id(node) not in self.routed:
                yield RawFinding(
                    "PRF1501",
                    node.lineno,
                    node.col_offset,
                    "raw pair-timing whose delta never reaches a probes/"
                    "trace/pulse sink: a stage measured here is invisible "
                    "to /metrics, the SLO engine and the flight-recorder "
                    "timeline — route it through _stat_stage/_stat_add, "
                    "tracer.record or probes.record_us/observe_us",
                )


class PerfTimingChecker(Checker):
    name = "perf-timing"
    rules = {
        "PRF1501": "raw perf_counter/monotonic pair-timing in a hot-path "
                   "package not routed through a probes/trace/pulse sink "
                   "(the flight recorder silently misses the stage)",
        "PRF1502": "pair-timing delta mixing monotonic and perf_counter "
                   "samples (no shared epoch: the delta is garbage)",
    }

    def check(self, ctx: FileContext) -> Iterator[RawFinding]:
        yield from self._scope(ctx.tree.body)
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._scope(node.body)
            elif isinstance(node, ast.Lambda):
                yield from self._scope([ast.Expr(value=node.body)])

    @staticmethod
    def _scope(body: list[ast.stmt]) -> Iterator[RawFinding]:
        scope = _FunctionScope()
        scope.collect(body)
        scope.analyze(body)
        yield from scope.findings()
