"""Lock-order analysis (DLK12xx) over the global acquisition graph.

DLK1201 — a nested lock acquisition that completes a CYCLE in the
program-wide acquisition-order graph (lockgraph.py: lexical nestings plus
``held -> may_acquire(callee)`` edges through resolved calls). Two
threads entering a cycle from different ends deadlock; with the coproc
tick deadline and raft election timers above them, even a *near* miss is
a latency cliff. Only unambiguous edges (lock identity pinned to one
owner, call resolution unique) participate — a false cycle from smeared
``_lock`` names would breed pragmas and erode trust in the real ones.

DLK1202 — unbounded blocking while holding a lock: ``.join()`` /
``.result()`` / ``.wait()`` / zero-arg ``.get()`` with **no timeout**
inside a held ``with <lock>`` region (directly or via the entry
lockset). A wedged peer — the failure mode the whole fault-domain layer
exists for — then convoys every waiter of that lock forever. The remedy
is the same discipline the engine's waiters follow: a timeout sized off
the fault envelope (``FaultPolicy.envelope_s`` / the governor's
``envelope_bound_s``), with the fallback decision made by the caller.

``str.join(iterable)`` and ``dict.get(key)`` take arguments and are
naturally exempt; only the zero-positional-arg, no-``timeout`` shapes of
the blocking APIs match.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.pandalint.affinity import Program
from tools.pandalint.checkers.base import Checker, RawFinding
from tools.pandalint.lockgraph import LockGraph

_BLOCKING_METHODS = {"join", "result", "wait", "get"}


class DeadlockChecker(Checker):
    name = "deadlocks"
    program_level = True
    rules = {
        "DLK1201": (
            "nested lock acquisition completes a lock-order cycle "
            "(potential deadlock)"
        ),
        "DLK1202": (
            "unbounded blocking call (join/result/wait/get without "
            "timeout) while holding a lock"
        ),
    }

    def check_program(
        self, program: Program, locks: LockGraph
    ) -> Iterator[tuple[str, RawFinding]]:
        for src, dst, site, witness in locks.cycle_edges():
            cycle = " -> ".join([src, *witness])
            yield (
                site.relpath,
                RawFinding(
                    "DLK1201",
                    site.lineno,
                    site.col,
                    f"acquiring {dst} while holding {src} completes the "
                    f"lock-order cycle {cycle}; two threads entering from "
                    f"different ends deadlock — impose one global order "
                    f"or drop {src} before this acquisition",
                ),
            )
        for fn in program.funcs.values():
            for call in locks.calls_of(fn):
                held = locks.held_at(fn, call)
                if not held:
                    continue
                f = call.func
                if not isinstance(f, ast.Attribute):
                    continue
                if f.attr not in _BLOCKING_METHODS:
                    continue
                if call.args:
                    continue  # str.join(x) / dict.get(k) / wait(t) shapes
                if any(kw.arg == "timeout" for kw in call.keywords):
                    continue
                yield (
                    fn.relpath,
                    RawFinding(
                        "DLK1202",
                        call.lineno,
                        call.col_offset,
                        f"{fn.qualname}() blocks in .{f.attr}() with no "
                        f"timeout while holding {sorted(held)}; a wedged "
                        f"peer convoys every waiter of the lock — size a "
                        f"timeout off the fault envelope, or move the "
                        f"wait outside the critical section",
                    ),
                )
