"""Blocking sleeps reaching async bodies past the literal-name rule.

RCT101 flags the literal ``time.sleep(...)`` inside ``async def`` — but a
blocking sleep stalls the reactor just as hard when it arrives renamed
(``from time import sleep`` / ``import time as t``) or laundered through a
module-local sync helper the coroutine calls. Both shapes have bitten real
asyncio codebases precisely because the obvious grep misses them.

The finjector is the ONE sanctioned home of deliberate blocking sleeps
(an injected delay/wedge fault must actually block — that IS the fault),
so files under ``redpanda_tpu/finjector`` are exempt wholesale rather
than carrying a pragma per effect site.

Heuristics (no type inference):

- SLP801: a call inside ``async def`` that resolves to ``time.sleep``
  through this module's import aliases (``from time import sleep [as x]``,
  ``import time as t`` + ``t.sleep``). The plain ``time.sleep`` spelling
  stays RCT101's finding — one rule per shape, nothing double-flags.
- SLP802: a bare-name call inside ``async def`` to a sync function
  defined in this module whose own body contains a blocking sleep (any
  spelling). Wrapping the helper in ``asyncio.to_thread`` /
  ``run_in_executor`` passes it as an argument, not a call, so offloaded
  helpers are naturally clean.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.pandalint.checkers.base import (
    Checker,
    FileContext,
    RawFinding,
    dotted,
    enclosing_async_functions,
    walk_in_function,
)



def _sleep_aliases(tree: ast.Module) -> tuple[set[str], set[str]]:
    """(bare names bound to time.sleep, module aliases bound to time —
    excluding the plain name ``time`` itself, which RCT101 owns)."""
    sleep_names: set[str] = set()
    time_aliases: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "sleep":
                    sleep_names.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "time" and alias.asname not in (None, "time"):
                    time_aliases.add(alias.asname)
    return sleep_names, time_aliases


def _is_blocking_sleep(call: ast.Call, sleep_names, time_aliases) -> bool:
    """Any spelling of a blocking time.sleep, aliased or literal."""
    name = dotted(call.func)
    if name == "time.sleep":
        return True
    if name in sleep_names:
        return True
    root, _, tail = name.partition(".")
    return root in time_aliases and tail == "sleep"


class SleepAsyncChecker(Checker):
    name = "sleep-async"
    rules = {
        "SLP801": "aliased blocking time.sleep inside async def",
        "SLP802": "sync helper that blocks in time.sleep called from async def",
    }

    def check(self, ctx: FileContext) -> Iterator[RawFinding]:
        rel = ctx.relpath.replace("\\", "/")
        if any(
            seg == "finjector" or seg.startswith("finjector.")
            for seg in rel.split("/")
        ):
            # deliberate blocking injection sites live here by design
            return
        sleep_names, time_aliases = _sleep_aliases(ctx.tree)
        # module-local sync functions whose bodies block in a sleep
        sleepy_helpers: set[str] = set()
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, ast.FunctionDef):
                continue
            for node in walk_in_function(fn):
                if isinstance(node, ast.Call) and _is_blocking_sleep(
                    node, sleep_names, time_aliases
                ):
                    sleepy_helpers.add(fn.name)
                    break
        for fn in enclosing_async_functions(ctx.tree):
            for node in walk_in_function(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted(node.func)
                if name == "time.sleep":
                    continue  # RCT101's finding, not ours
                if _is_blocking_sleep(node, sleep_names, time_aliases):
                    yield RawFinding(
                        "SLP801",
                        node.lineno,
                        node.col_offset,
                        f"{name or 'sleep'}() is time.sleep in disguise and "
                        f"blocks the event loop inside async {fn.name}(); "
                        f"use asyncio.sleep",
                    )
                elif (
                    isinstance(node.func, ast.Name)
                    and node.func.id in sleepy_helpers
                ):
                    yield RawFinding(
                        "SLP802",
                        node.lineno,
                        node.col_offset,
                        f"{node.func.id}() blocks in time.sleep and is "
                        f"called on the loop inside async {fn.name}(); "
                        f"offload with asyncio.to_thread",
                    )
