"""Cross-shard isolation: a shard worker touches ONLY its own shard.

The host-stage pool (coproc/host_pool.py) gets its correctness from a
single discipline: every per-shard worker body produces exactly one
``_HostShard`` and never writes anybody else's — no sibling shard slots,
no launch/engine attributes, no partition-map entries. Fan-in back to
shared state happens after ``pool.run()`` returns, on the submitter
thread (or under the owner's lock). The reference enforces the same
contract structurally — Seastar shards mutate another shard's partition
map only via ``submit_to`` onto its owning reactor — but Python threads
share everything, so the contract here is convention, and this checker
is what keeps the convention honest.

Naming convention the checker leans on (engine.py follows it): per-shard
worker bodies carry a ``shard`` name token (``_run_columnar_shard``,
``_frame_shard``); launch-wide coordinators use ``sharded``
(``_dispatch_sharded``) and are exempt — they run on the submitter thread
after the fan-in barrier and own the merge.

Rules:

- SHD601 — a worker writes through a shards table (``launch._shards[i]``,
  ``shards[j].field``): reaching a sibling shard by index is exactly the
  cross-shard mutation the pool forbids.
- SHD602 — a worker writes an attribute/element of a SHARED parameter
  (``self``, ``launch``, ``plan``, …) outside a ``with <lock>:`` block.
  Workers write their own shard (a shard-named parameter or an object
  they constructed) and plain locals; results travel via return values.
- SHD603 — any function in scope mutates a queue's internal buffer
  (``q.queue.append(...)``, ``q.queue[i] = ...``): bypassing the Queue
  mutex corrupts the submit/harvest handoff. Use ``put()``/``get()``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.pandalint.checkers.base import (
    Checker,
    FileContext,
    RawFinding,
    dotted,
    walk_in_function,
)

_QUEUE_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert",
    "pop", "popleft", "remove", "clear",
}


def _name_tokens(name: str) -> set[str]:
    return set(name.lower().split("_"))


def _is_shard_worker(fn: ast.AST) -> bool:
    """Per-shard worker bodies carry a 'shard' token; 'sharded' names the
    launch-wide coordinators (submitter-thread fan-out/fan-in) instead."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    return "shard" in _name_tokens(fn.name)


def _params(fn) -> set[str]:
    a = fn.args
    names = [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


def _root_name(node: ast.expr) -> str | None:
    """Leftmost Name of an Attribute/Subscript chain, None otherwise."""
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _shards_subscript(node: ast.expr) -> bool:
    """Does the chain index into a shards table (``*._shards[...]`` /
    ``shards[...]``) anywhere along the way?"""
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
        if isinstance(node, ast.Subscript):
            v = node.value
            coll = v.attr if isinstance(v, ast.Attribute) else (
                v.id if isinstance(v, ast.Name) else ""
            )
            if "shards" in coll.lower():
                return True
        node = node.value
    return False


def _queue_internal(node: ast.expr) -> bool:
    """`<something>.queue` where the owner looks like a queue object —
    the stdlib Queue's internal deque (``q.queue``), not ``put``/``get``."""
    if not (isinstance(node, ast.Attribute) and node.attr == "queue"):
        return False
    owner = node.value
    tail = owner.attr if isinstance(owner, ast.Attribute) else (
        owner.id if isinstance(owner, ast.Name) else ""
    )
    tail = tail.lower()
    return tail.endswith("_q") or tail.endswith("_queue") or "queue" in tail or tail == "q"


def _write_targets(node: ast.AST) -> list[ast.expr]:
    if isinstance(node, ast.Assign):
        return list(node.targets)
    if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        return [node.target]
    return []


def _flatten(targets: list[ast.expr]) -> Iterator[ast.expr]:
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            yield from _flatten(list(t.elts))
        else:
            yield t


def _is_lock_with(node: ast.AST) -> bool:
    if not isinstance(node, (ast.With, ast.AsyncWith)):
        return False
    for item in node.items:
        ctx = item.context_expr
        if isinstance(ctx, ast.Call):
            ctx = ctx.func
        if "lock" in dotted(ctx).lower():
            return True
    return False


class CrossShardChecker(Checker):
    name = "cross-shard"
    rules = {
        "SHD601": "shard worker writes through a shards table (sibling shard mutation)",
        "SHD602": "shard worker writes shared owner state outside a lock",
        "SHD603": "direct mutation of a Queue's internal buffer (bypasses its mutex)",
    }

    def check(self, ctx: FileContext) -> Iterator[RawFinding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield from self._check_queue_internals(fn)
            if _is_shard_worker(fn):
                shared = {
                    p for p in _params(fn) if "shard" not in _name_tokens(p)
                }
                yield from self._check_worker(fn, fn.name, shared, locked=False)

    # ---------------------------------------------------------- SHD601/602
    def _check_worker(
        self, node: ast.AST, fn_name: str, shared: set[str], locked: bool
    ) -> Iterator[RawFinding]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs have their own execution context
            child_locked = locked or _is_lock_with(child)
            for target in _flatten(_write_targets(child)):
                if isinstance(target, ast.Name):
                    continue  # plain locals are the worker's own business
                if _shards_subscript(target):
                    yield RawFinding(
                        "SHD601",
                        target.lineno,
                        target.col_offset,
                        f"{fn_name}() writes a sibling shard's slot through "
                        f"a shards table; a worker owns exactly one shard",
                    )
                    continue
                root = _root_name(target)
                if root in shared and not child_locked:
                    yield RawFinding(
                        "SHD602",
                        target.lineno,
                        target.col_offset,
                        f"{fn_name}() mutates shared '{root}' from a shard "
                        f"worker without a lock; return the result and merge "
                        f"after pool.run(), or take the owner's lock",
                    )
            yield from self._check_worker(child, fn_name, shared, child_locked)

    # -------------------------------------------------------------- SHD603
    def _check_queue_internals(self, fn) -> Iterator[RawFinding]:
        for node in walk_in_function(fn):
            hit: ast.expr | None = None
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                if node.func.attr in _QUEUE_MUTATORS and _queue_internal(
                    node.func.value
                ):
                    hit = node.func.value
            else:
                for target in _flatten(_write_targets(node)):
                    probe = target
                    if isinstance(probe, ast.Subscript):
                        probe = probe.value
                    if isinstance(probe, ast.Attribute) and _queue_internal(probe):
                        hit = probe
                    elif _queue_internal(target):
                        hit = target
            if hit is not None:
                yield RawFinding(
                    "SHD603",
                    node.lineno,
                    node.col_offset,
                    f"{fn.name}() reaches into a Queue's internal buffer; "
                    f"only put()/get() hold the mutex",
                )
