"""Unbounded producer-side buffering in hot paths (backpressure ratchet).

The resource_mgmt budget plane exists so a produce flood degrades into
judged, counted sheds — but one unbounded ``Queue()`` or an append-only
list between a producer and a slower consumer silently re-opens the exact
failure the accounts close: memory grows with offered load instead of with
admitted load, and the OOM arrives with no shed counter, no pressure
signal, no journal entry. This checker makes bounded-or-budgeted the
default posture in the hot-path packages (``redpanda_tpu/{kafka,rpc,
coproc,raft}``); deliberate exceptions carry a reasoned pragma naming the
bound that actually exists (an admission gate upstream, a drain that runs
in the same tick, a shutdown-only path).

Heuristic scope (no type inference):

- BPR1401: an unbounded queue CONSTRUCTION — ``asyncio.Queue()`` /
  ``queue.Queue()`` (any import alias) with no capacity, an explicit
  literal ``maxsize=0``, or ``queue.SimpleQueue()`` (unboundable by
  design). A non-literal capacity gets the benefit of the doubt.
- BPR1402: a ``.put_nowait(...)`` whose receiver resolves — same-class
  ``self._x`` attribute or a local/module name assigned in this file — to
  an unbounded queue: the producer-side push that grows without waiting.
  Unresolvable receivers (parameters, foreign objects) stay silent
  rather than guessing.
- BPR1403: ``self.<buffer>.append(...)`` inside ``async def`` where the
  attribute was initialized to a bare list in this class and its name
  says accumulation (pending/queue/backlog/buffer/inflight/batch) — the
  list-append flood shape — UNLESS the same function also acquires a
  budget (a call whose dotted name mentions ``acquire``/``admit``: the
  bytes were admitted before they were parked).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from tools.pandalint.checkers.base import (
    Checker,
    FileContext,
    RawFinding,
    dotted,
)

_HOT_PREFIXES = (
    "redpanda_tpu/kafka/",
    "redpanda_tpu/rpc/",
    "redpanda_tpu/coproc/",
    "redpanda_tpu/raft/",
)

_BUFFERISH = re.compile(
    r"(pending|queue|backlog|buffer|inflight|batch)", re.IGNORECASE
)
_BUDGET_CALL = re.compile(r"(acquire|admit)", re.IGNORECASE)

# dotted spellings that construct a queue once asyncio/queue aliases are
# normalized; SimpleQueue has no maxsize parameter at all
_QUEUE_TAILS = {"Queue", "LifoQueue", "PriorityQueue"}
_ALWAYS_UNBOUNDED_TAILS = {"SimpleQueue"}


def _queue_modules(tree: ast.Module) -> tuple[set[str], set[str]]:
    """(module aliases naming asyncio/queue, bare names imported from
    them that look like queue classes)."""
    mod_aliases: set[str] = set()
    bare_names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name in ("asyncio", "queue"):
                    mod_aliases.add(alias.asname or alias.name)
        elif isinstance(node, ast.ImportFrom) and node.module in (
            "asyncio", "queue", "asyncio.queues",
        ):
            for alias in node.names:
                if alias.name in _QUEUE_TAILS | _ALWAYS_UNBOUNDED_TAILS:
                    bare_names.add(alias.asname or alias.name)
    return mod_aliases, bare_names


def _classify_queue_call(call: ast.Call, mod_aliases, bare_names):
    """None = not a queue construction; else True when UNBOUNDED."""
    name = dotted(call.func)
    root, _, tail = name.partition(".")
    if name in bare_names:
        tail = name  # from-import: the bare name IS the class
    elif not (root in mod_aliases and tail in _QUEUE_TAILS | _ALWAYS_UNBOUNDED_TAILS):
        return None
    if tail in _ALWAYS_UNBOUNDED_TAILS:
        return True
    cap = call.args[0] if call.args else None
    for kw in call.keywords:
        if kw.arg == "maxsize":
            cap = kw.value
    if cap is None:
        return True
    if isinstance(cap, ast.Constant) and cap.value == 0:
        return True  # maxsize=0 IS the unbounded spelling
    return False  # literal bound or non-literal expression: trusted


def _receiver_of(call: ast.Call) -> str:
    """Dotted receiver of an attribute call: `self._q.put_nowait` -> the
    `self._q` part ('' when the callee isn't an attribute chain)."""
    if isinstance(call.func, ast.Attribute):
        return dotted(call.func.value)
    return ""


class BackpressureChecker(Checker):
    name = "backpressure"
    rules = {
        "BPR1401": "unbounded queue construction in a hot-path package",
        "BPR1402": "put_nowait onto an unbounded queue (producer-side growth)",
        "BPR1403": "async list-append buffering with no bound or acquired budget",
    }

    def check(self, ctx: FileContext) -> Iterator[RawFinding]:
        rel = ctx.relpath.replace("\\", "/")
        if not rel.startswith(_HOT_PREFIXES):
            return
        mod_aliases, bare_names = _queue_modules(ctx.tree)
        # nearest enclosing class per node (innermost wins)
        class_of: dict[ast.AST, str] = {}

        def _map_classes(node: ast.AST, cls_name: str | None) -> None:
            for child in ast.iter_child_nodes(node):
                inner = child.name if isinstance(child, ast.ClassDef) else cls_name
                if cls_name is not None:
                    class_of[child] = cls_name
                _map_classes(child, inner)

        _map_classes(ctx.tree, None)
        # pass 1: constructions. Bounded-ness maps for pass 2/3:
        #   ('self', ClassName, attr) / ('name', name)
        unbounded: set[tuple] = set()
        list_attrs: set[tuple[str, str]] = set()  # (cls, attr) bare lists
        findings: list[RawFinding] = []

        def record_assign(target: ast.expr, value: ast.expr, scope_cls: str | None):
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
                and scope_cls is not None
                and isinstance(value, ast.List)
                and not value.elts
                and _BUFFERISH.search(target.attr)
            ):
                list_attrs.add((scope_cls, target.attr))
            if not isinstance(value, ast.Call):
                return
            verdict = _classify_queue_call(value, mod_aliases, bare_names)
            if verdict is None:
                return
            key = None
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
                and scope_cls is not None
            ):
                key = ("self", scope_cls, target.attr)
            elif isinstance(target, ast.Name):
                key = ("name", target.id)
            if verdict:
                findings.append(RawFinding(
                    "BPR1401", value.lineno, value.col_offset,
                    f"{dotted(value.func)}() has no capacity: memory grows "
                    f"with offered load, not admitted load — pass maxsize "
                    f"(or acquire from a resource_mgmt account and pragma "
                    f"the bound)",
                ))
                if key is not None:
                    unbounded.add(key)

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    record_assign(t, node.value, class_of.get(node))
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                record_assign(node.target, node.value, class_of.get(node))

        yield from findings
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "put_nowait"
            ):
                continue
            recv = _receiver_of(node)
            key = None
            if recv.startswith("self."):
                cls_name = class_of.get(node)
                if cls_name is not None:
                    key = ("self", cls_name, recv[5:])
            elif recv and "." not in recv:
                key = ("name", recv)
            if key is not None and key in unbounded:
                yield RawFinding(
                    "BPR1402", node.lineno, node.col_offset,
                    f"{recv}.put_nowait() onto an unbounded queue: the "
                    f"producer never waits and never sheds — bound the "
                    f"queue or admit the bytes through a budget first",
                )

        # pass 3: async list-append buffering
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            cls_name = class_of.get(fn)
            if cls_name is None:
                continue
            has_budget = any(
                isinstance(n, ast.Call) and _BUDGET_CALL.search(dotted(n.func) or "")
                for n in ast.walk(fn)
            )
            if has_budget:
                continue
            for node in ast.walk(fn):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "append"
                ):
                    continue
                recv = _receiver_of(node)
                if not recv.startswith("self."):
                    continue
                attr = recv[5:]
                if (cls_name, attr) in list_attrs:
                    yield RawFinding(
                        "BPR1403", node.lineno, node.col_offset,
                        f"{recv}.append() buffers producer-side in async "
                        f"{fn.name}() with no bound and no acquired "
                        f"budget — cap it or reserve from a "
                        f"resource_mgmt account before parking bytes",
                    )
