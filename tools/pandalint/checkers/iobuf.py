"""iobuf copy discipline: keep buffer views zero-copy on the data plane.

The IOBuf/memoryview machinery exists so record payloads cross the broker
without materializing; a ``bytes(view)`` inside a per-record loop silently
reintroduces the O(n) copies the fragment design removed. Two shapes:

- IOB401: ``bytes(x)`` / ``bytearray(x)`` lexically inside a ``for`` /
  ``while`` body. Loop-exit conversions (``return bytes(out)``) are the
  legitimate single materialization at the API boundary and are ignored.
- IOB402: ``crc32c(bytes(x))``-style calls anywhere — the CRC/hash helpers
  accept any buffer, so the copy is pure waste on the hottest validation
  path (produce CRC covers every batch byte).
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.pandalint.checkers.base import Checker, FileContext, RawFinding, dotted

_HASH_CONSUMERS = {
    "crc32c",
    "crc32c_update",
    "crc32c_extend",
    "crc32c_many",
    "xxhash64",
    "xxhash32",
    "crc32",
    "adler32",
}


def _is_copy_call(node: ast.Call) -> bool:
    return (
        isinstance(node.func, ast.Name)
        and node.func.id in ("bytes", "bytearray")
        and bool(node.args)  # bytes() / bytearray() constructors are fine
        and not isinstance(node.args[0], ast.Constant)  # bytes(0), bytearray(n)
    )


class IobufCopyChecker(Checker):
    name = "iobuf-copy"
    rules = {
        "IOB401": "bytes()/bytearray() view materialization inside a loop",
        "IOB402": "bytes() copy fed straight to a buffer-accepting CRC/hash",
    }

    def check(self, ctx: FileContext) -> Iterator[RawFinding]:
        findings: list[RawFinding] = []

        class V(ast.NodeVisitor):
            def __init__(self) -> None:
                self.loops = 0

            def _loop(self, node) -> None:
                self.loops += 1
                self.generic_visit(node)
                self.loops -= 1

            visit_For = _loop
            visit_AsyncFor = _loop
            visit_While = _loop

            def visit_Return(self, node: ast.Return) -> None:
                pass  # single loop-exit materialization: the API boundary

            def visit_Raise(self, node: ast.Raise) -> None:
                pass

            def visit_Call(self, node: ast.Call) -> None:
                if self.loops and _is_copy_call(node):
                    findings.append(
                        RawFinding(
                            "IOB401",
                            node.lineno,
                            node.col_offset,
                            "per-iteration bytes() materialization copies "
                            "the view each pass; keep the memoryview or "
                            "hoist the copy out of the loop",
                        )
                    )
                self.generic_visit(node)

        V().visit(ctx.tree)

        # IOB402 applies everywhere, including return statements
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func).rsplit(".", 1)[-1]
            if name in _HASH_CONSUMERS:
                for arg in node.args:
                    if isinstance(arg, ast.Call) and _is_copy_call(arg):
                        findings.append(
                            RawFinding(
                                "IOB402",
                                arg.lineno,
                                arg.col_offset,
                                f"{name}() accepts any buffer — the bytes() "
                                f"copy of its argument is pure overhead; "
                                f"pass the view directly",
                            )
                        )
        yield from findings
