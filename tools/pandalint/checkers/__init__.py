"""Checker registry."""

from __future__ import annotations

from tools.pandalint.checkers.base import Checker, FileContext
from tools.pandalint.checkers.reactor import ReactorChecker
from tools.pandalint.checkers.hotpath import (
    HotPathSyncChecker,
    HotPathNumpyChecker,
    HotPathControlChecker,
)
from tools.pandalint.checkers.tasks import TaskHygieneChecker
from tools.pandalint.checkers.iobuf import IobufCopyChecker
from tools.pandalint.checkers.enginesync import EngineSyncChecker
from tools.pandalint.checkers.crossshard import CrossShardChecker
from tools.pandalint.checkers.locks import LockRpcChecker
from tools.pandalint.checkers.sleeps import SleepAsyncChecker
from tools.pandalint.checkers.excepts import BareExceptChecker
from tools.pandalint.checkers.hdrrecord import HdrRecordChecker
from tools.pandalint.checkers.races import RaceChecker
from tools.pandalint.checkers.deadlocks import DeadlockChecker
from tools.pandalint.checkers.tracectx import TraceCtxChecker
from tools.pandalint.checkers.meshctx import MeshCtxChecker
from tools.pandalint.checkers.backpressure import BackpressureChecker
from tools.pandalint.checkers.perftiming import PerfTimingChecker
from tools.pandalint.checkers.metricshygiene import MetricsHygieneChecker
from tools.pandalint.lifecycle import LifecycleChecker

ALL_CHECKERS: tuple[type[Checker], ...] = (
    ReactorChecker,
    HotPathSyncChecker,
    HotPathNumpyChecker,
    HotPathControlChecker,
    TaskHygieneChecker,
    IobufCopyChecker,
    EngineSyncChecker,
    CrossShardChecker,
    LockRpcChecker,
    SleepAsyncChecker,
    BareExceptChecker,
    HdrRecordChecker,
    RaceChecker,
    DeadlockChecker,
    TraceCtxChecker,
    MeshCtxChecker,
    BackpressureChecker,
    PerfTimingChecker,
    MetricsHygieneChecker,
    LifecycleChecker,
)


def rule_catalog() -> dict[str, tuple[str, str]]:
    """rule id -> (checker name, description)."""
    out: dict[str, tuple[str, str]] = {}
    for cls in ALL_CHECKERS:
        for rule, desc in cls.rules.items():
            out[rule] = (cls.name, desc)
    return out


__all__ = ["ALL_CHECKERS", "Checker", "FileContext", "rule_catalog"]
