"""Trace-context propagation: no ctx-less wire framing in a traced region.

Post-pandascope, the rpc wire carries a compact trace-context block
(rpc/wire.py TraceContext) so a produce's trace survives the hop onto the
brokers it replicates through. ``Transport.send`` threads the ambient
context automatically — but code that frames wire messages BY HAND inside
a live ``tracer.span(...)`` block silently truncates the distributed trace
at that hop: the bytes go out version-0, the peer's handler span never
JOINs, and the cluster-assembled view ends at the sender. Post-propagation
that is a bug, not a style choice.

Heuristic scope (no type inference): lexically inside a ``with`` block
whose context expression is a ``*.span(...)`` call on a tracer-named
receiver (``tracer.span``, ``self._tracer.span``):

- TRC1201 — a call resolving to ``rpc.wire.frame(...)`` (module alias or
  ``from``-import) without a ``trace_ctx=`` keyword. Passing the keyword —
  even an explicitly-``None`` variable — is the signal the author decided
  what rides the wire; omitting it is the silent drop.
- TRC1202 — hand-rolled ``rpc.wire.Header(...)`` construction. A raw
  header can never carry context (``frame(..., trace_ctx=)`` is the only
  propagating entry point), so building one in a traced region bypasses
  propagation entirely; go through ``frame`` or move the framing out of
  the span.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.pandalint.checkers.base import (
    Checker,
    FileContext,
    RawFinding,
    dotted,
)

_WIRE_MODULE = "redpanda_tpu.rpc.wire"


def _wire_aliases(tree: ast.Module) -> tuple[set[str], set[str], set[str]]:
    """(names bound to wire.frame, names bound to wire.Header, module
    aliases bound to the rpc.wire module). The conventional bare ``wire``
    receiver counts as a module alias even without a resolvable import —
    fixtures and vendored copies must not dodge the rule on import shape."""
    frame_names: set[str] = set()
    header_names: set[str] = set()
    wire_mods: set[str] = {"wire"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module == _WIRE_MODULE:
                for alias in node.names:
                    if alias.name == "frame":
                        frame_names.add(alias.asname or alias.name)
                    elif alias.name == "Header":
                        header_names.add(alias.asname or alias.name)
            elif node.module == "redpanda_tpu.rpc":
                for alias in node.names:
                    if alias.name == "wire":
                        wire_mods.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == _WIRE_MODULE and alias.asname:
                    wire_mods.add(alias.asname)
    return frame_names, header_names, wire_mods


def _is_tracer_span_with(node: ast.AST) -> bool:
    if not isinstance(node, (ast.With, ast.AsyncWith)):
        return False
    for item in node.items:
        ctx = item.context_expr
        if isinstance(ctx, ast.Call):
            name = dotted(ctx.func)
            if name.endswith(".span") and "trace" in name.lower():
                return True
    return False


class TraceCtxChecker(Checker):
    name = "trace-ctx"
    rules = {
        "TRC1201": "wire.frame(...) inside a tracer.span(...) block without trace_ctx= — the send silently drops the trace at this hop",
        "TRC1202": "hand-rolled wire.Header(...) inside a tracer.span(...) block — raw headers can never carry trace context",
    }

    def check(self, ctx: FileContext) -> Iterator[RawFinding]:
        frame_names, header_names, wire_mods = _wire_aliases(ctx.tree)
        for fn in ast.walk(ctx.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._walk(
                    fn, fn.name, False, frame_names, header_names, wire_mods
                )

    def _walk(
        self, node, fn_name, in_span, frame_names, header_names, wire_mods
    ) -> Iterator[RawFinding]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs execute in their own (unspanned) scope
            child_in_span = in_span or _is_tracer_span_with(child)
            if child_in_span and isinstance(child, ast.Call):
                func = child.func
                is_frame = (
                    isinstance(func, ast.Name) and func.id in frame_names
                ) or (
                    isinstance(func, ast.Attribute)
                    and func.attr == "frame"
                    and dotted(func.value) in wire_mods
                )
                is_header = (
                    isinstance(func, ast.Name) and func.id in header_names
                ) or (
                    isinstance(func, ast.Attribute)
                    and func.attr == "Header"
                    and dotted(func.value) in wire_mods
                )
                if is_frame and not any(
                    kw.arg == "trace_ctx" for kw in child.keywords
                ):
                    yield RawFinding(
                        "TRC1201",
                        child.lineno,
                        child.col_offset,
                        f"{fn_name}() frames a wire message inside a live "
                        f"tracer.span block without trace_ctx= — the "
                        f"ambient trace dies at this hop; pass "
                        f"trace_ctx=... (None is an explicit decision) or "
                        f"send through Transport.send",
                    )
                elif is_header:
                    yield RawFinding(
                        "TRC1202",
                        child.lineno,
                        child.col_offset,
                        f"{fn_name}() hand-rolls a wire.Header inside a "
                        f"live tracer.span block — raw headers cannot "
                        f"carry trace context; use wire.frame(..., "
                        f"trace_ctx=) or move the framing out of the span",
                    )
            yield from self._walk(
                child, fn_name, child_in_span, frame_names, header_names,
                wire_mods,
            )
