"""Fault classification discipline: no silent `except Exception` in coproc.

PR 4 routed every formerly-silent swallow in the engine through
``faults.note_failure`` so each degradation path shows up as a
``coproc_failures_total{domain,kind}`` series — an invisible fallback is
how a broker runs demoted for a week before anyone notices. This checker
makes that a ratchet: a broad catch added to ``redpanda_tpu/coproc`` must
either classify what it swallowed or say (with a reasoned pragma) why it
is allowed to stay silent.

Heuristic scope (no type inference), confined to ``redpanda_tpu/coproc``:

- EXC901: an ``except Exception`` / ``except BaseException`` handler whose
  body neither calls ``note_failure`` (any dotted spelling) nor re-raises.
  A handler that re-raises (bare ``raise`` or ``raise exc`` anywhere in
  its body, including conditionally) propagates rather than swallows and
  is exempt.
- EXC902: a bare ``except:`` — strictly worse (it also eats
  CancelledError/SystemExit), flagged regardless of body.

Sanctioned shapes that never flag:

- **Import probes**: a ``try`` whose body contains an ``import`` —
  "is the native build / optional dep present" is a configuration
  decision made once, not a runtime fault (engine hot paths that *do*
  want the demotion visible classify it anyway, e.g. ``_pack_values``'s
  ``note_failure("native_lib", ...)``).
- **faults.py itself**: the classifier's own retry envelope re-raises at
  exhaustion; it is the one module allowed to reason about raw failures.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.pandalint.checkers.base import (
    Checker,
    FileContext,
    RawFinding,
    dotted,
)

_BROAD = {"Exception", "BaseException"}


def _catches_broad(handler: ast.ExceptHandler) -> bool:
    """True when the handler type includes Exception/BaseException (bare
    handlers are EXC902's finding, not this predicate's)."""
    t = handler.type
    if t is None:
        return False
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for node in types:
        name = dotted(node)
        if name in _BROAD or name.split(".")[-1] in _BROAD:
            return True
    return False


def _body_walk(handler: ast.ExceptHandler) -> Iterator[ast.AST]:
    """Walk the handler body WITHOUT descending into nested function defs
    (a classification inside a nested callback only runs if something
    calls it — it does not classify THIS swallow)."""
    stack: list[ast.AST] = list(handler.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _classifies_or_reraises(handler: ast.ExceptHandler) -> bool:
    for node in _body_walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name == "note_failure" or name.endswith(".note_failure"):
                return True
    return False


def _try_imports(try_node: ast.Try) -> bool:
    return any(
        isinstance(stmt, (ast.Import, ast.ImportFrom)) for stmt in try_node.body
    )


class BareExceptChecker(Checker):
    name = "bare-except"
    rules = {
        "EXC901": "except Exception in coproc without a faults.note_failure "
                  "classification (or re-raise) in the handler body",
        "EXC902": "bare except: swallows CancelledError/SystemExit too",
    }

    def check(self, ctx: FileContext) -> Iterator[RawFinding]:
        rel = ctx.relpath.replace("\\", "/")
        if rel.endswith("/faults.py"):
            # the classification module itself: its retry envelope holds
            # raw failures by design and re-delivers them at exhaustion
            return
        for try_node in ast.walk(ctx.tree):
            if not isinstance(try_node, ast.Try):
                continue
            imports = _try_imports(try_node)
            for handler in try_node.handlers:
                if handler.type is None:
                    yield RawFinding(
                        "EXC902",
                        handler.lineno,
                        handler.col_offset,
                        "bare except: catches CancelledError and SystemExit "
                        "too; catch Exception and classify via "
                        "faults.note_failure",
                    )
                    continue
                if not _catches_broad(handler):
                    continue
                if imports:
                    continue  # import probe: a configuration, not a fault
                if _classifies_or_reraises(handler):
                    continue
                yield RawFinding(
                    "EXC901",
                    handler.lineno,
                    handler.col_offset,
                    "except Exception swallowed without classification: "
                    "call faults.note_failure(domain, exc) so the "
                    "degradation lands in coproc_failures_total, or "
                    "re-raise",
                )
