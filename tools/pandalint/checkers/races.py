"""Eraser-style lockset analysis over executor affinity (RAC11xx).

The engine's execution-context zoo (asyncio loop, coproc-tick executor,
harvester/fetch daemons, host-pool shard workers, finalizers) makes
"which thread touches this attribute" the question behind four of the
last five review-round bugs (breaker ``_notify`` re-read, duplicate jit
trace, mask-slot claim protocol, waiter/envelope double-fetch). This
checker asks it mechanically, per class attribute:

1. every ``self.<attr>`` / ``Cls.<attr>`` access site in the class's
   methods is collected with the **contexts** that can execute the
   enclosing function (affinity.Program) and the **lockset** held there
   (lockgraph: lexical ``with`` stack + the function's entry lockset, so
   "caller holds self._lock" contracts are seen through);
2. construction (``__init__``/``__post_init__``/``__new__``) is exempt —
   the object is not yet published;
3. two sites *race* when their context sets contain distinct contexts,
   or share a pool-backed context (executor / pool_worker — pools race
   themselves; the duplicate-jit-trace shape);
4. a **write** whose lockset shares nothing with some racing access is
   RAC1101; an **unlocked read** racing writes that are themselves
   consistently locked is RAC1102 (the torn-snapshot shape: ``stats()``
   reading multi-field probe state the calibrator updates under a lock).

Like every rule here, findings are silenced only by a reasoned pragma —
an attribute genuinely published by a queue/Event handoff (a
happens-before edge the lockset model cannot see) carries its
justification in the source instead of silently passing.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Iterator

from tools.pandalint.affinity import (
    LIFECYCLE,
    Program,
    ProgFunc,
    contexts_race,
)
from tools.pandalint.checkers.base import Checker, RawFinding
from tools.pandalint.lockgraph import LockGraph

_CTOR_METHODS = {"__init__", "__post_init__", "__new__"}


@dataclass
class _Site:
    fn: ProgFunc
    node: ast.AST
    lineno: int
    col: int
    write: bool
    contexts: frozenset
    lockset: frozenset

    def where(self) -> str:
        return f"{self.fn.relpath}:{self.lineno}"


def _ctx_label(ctxs: frozenset) -> str:
    return "{" + ",".join(sorted(ctxs)) + "}"


class RaceChecker(Checker):
    name = "races"
    program_level = True
    rules = {
        "RAC1101": (
            "attribute written without any lock shared with a concurrent "
            "access from another execution context"
        ),
        "RAC1102": (
            "unlocked read of an attribute whose concurrent writes are "
            "consistently locked (torn-snapshot read)"
        ),
    }

    def check_program(
        self, program: Program, locks: LockGraph
    ) -> Iterator[tuple[str, RawFinding]]:
        # (modkey, class) -> attr -> [sites]
        buckets: dict[tuple[str, str], dict[str, list[_Site]]] = {}
        for fn in program.funcs.values():
            if fn.cls is None or not fn.contexts:
                continue
            if fn.name in _CTOR_METHODS or LIFECYCLE.search(fn.name):
                continue
            attrs = buckets.setdefault((fn.modkey, fn.cls), {})
            for node, write in self._attr_accesses(program, fn):
                attrs.setdefault(node.attr, []).append(
                    _Site(
                        fn,
                        node,
                        node.lineno,
                        node.col_offset,
                        write,
                        frozenset(fn.contexts),
                        locks.held_at(fn, node),
                    )
                )
        findings: list[tuple[str, RawFinding]] = []
        for (modkey, cls), attrs in sorted(buckets.items()):
            for attr, sites in sorted(attrs.items()):
                findings.extend(self._judge(cls, attr, sites))
        # stable order; the engine re-sorts per file anyway
        for item in sorted(findings, key=lambda kv: (kv[0], kv[1].line)):
            yield item

    # ------------------------------------------------------------ collection
    def _attr_accesses(
        self, program: Program, fn: ProgFunc
    ) -> Iterator[tuple[ast.Attribute, bool]]:
        """(attribute node, is_write) for self./cls./ClassName. receivers,
        skipping method references (``self.helper(...)`` is a call, not
        shared data) and nested function bodies (their own ProgFuncs)."""
        # the receiver must be the function's actual first parameter (or
        # the class name for ClassVar writes): a classmethod constructor
        # rebinding `self = cls.__new__(cls)` mutates a LOCAL instance
        # that nothing can race yet
        args = getattr(fn.node, "args", None)
        first_param = ""
        if args is not None:
            pos = args.posonlyargs + args.args
            if pos:
                first_param = pos[0].arg
        if first_param not in ("self", "cls"):
            return
        stack = list(ast.iter_child_nodes(fn.node))
        aug_targets: set[int] = set()
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(node, ast.AugAssign) and isinstance(
                node.target, ast.Attribute
            ):
                aug_targets.add(id(node.target))
            if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name
            ):
                recv = node.value.id
                if recv == first_param or recv == fn.cls:
                    is_method = bool(
                        program._methods.get((fn.cls, node.attr))
                    )
                    if not is_method:
                        write = isinstance(
                            node.ctx, (ast.Store, ast.Del)
                        ) or id(node) in aug_targets
                        yield node, write
            stack.extend(ast.iter_child_nodes(node))

    # ------------------------------------------------------------ judgement
    def _judge(
        self, cls: str, attr: str, sites: list[_Site]
    ) -> Iterator[tuple[str, RawFinding]]:
        writes = [s for s in sites if s.write]
        if not writes:
            return
        # blame the DEFICIENT side of each racing disjoint-lockset pair:
        # an unlocked (or differently-locked) write is RAC1101 at the
        # write; a lone unlocked read against disciplined locked writes
        # is RAC1102 at the read (the stats()-style torn snapshot)
        flagged_writes: set[int] = set()
        for w in writes:
            partner = next(
                (
                    s
                    for s in sites
                    if (s is not w or contexts_race(w.contexts, w.contexts))
                    and contexts_race(w.contexts, s.contexts)
                    and not (w.lockset & s.lockset)
                    and (not w.lockset or s.lockset)
                ),
                None,
            )
            if partner is not None:
                flagged_writes.add(id(w))
                held = (
                    f"holding {sorted(w.lockset)}"
                    if w.lockset
                    else "with no lock held"
                )
                yield (
                    w.fn.relpath,
                    RawFinding(
                        "RAC1101",
                        w.lineno,
                        w.col,
                        f"{w.fn.qualname}() writes {cls}.{attr} "
                        f"{held} in context {_ctx_label(w.contexts)}, "
                        f"racing the access at {partner.where()} in "
                        f"{_ctx_label(partner.contexts)} with no common "
                        f"lock — serialize both sites on one lock, or "
                        f"suppress with the happens-before reason "
                        f"(queue/Event handoff)",
                    ),
                )
        for r in sites:
            if r.write:
                continue
            racing = [
                w
                for w in writes
                if contexts_race(r.contexts, w.contexts)
            ]
            if not racing:
                continue
            # RAC1102 only when the write side is disciplined (every
            # racing write holds a lock AND none was already blamed as
            # RAC1101 — a write under lock A racing a read under
            # disjoint lock B is ONE defect, blamed once at the write):
            # double-flagging every reader would bury the real finding
            if any(
                not w.lockset or id(w) in flagged_writes for w in racing
            ):
                continue
            miss = next(
                (w for w in racing if not (r.lockset & w.lockset)), None
            )
            if miss is not None:
                yield (
                    r.fn.relpath,
                    RawFinding(
                        "RAC1102",
                        r.lineno,
                        r.col,
                        f"{r.fn.qualname}() reads {cls}.{attr} without "
                        f"{sorted(miss.lockset)} in context "
                        f"{_ctx_label(r.contexts)} while "
                        f"{miss.fn.qualname}() ({miss.where()}) writes it "
                        f"under that lock — take the lock for the read "
                        f"(torn multi-field snapshots) or suppress with "
                        f"the reason a stale value is acceptable",
                    ),
                )
