"""Histogram record discipline: HdrHist.record is a read-modify-write.

``HdrHist.record()`` bumps a bucket dict, a total, a sum and a max — four
plain read-modify-writes with no internal lock (utils/hdr.py keeps the hot
path to integer math on purpose; readers get GIL-atomic snapshots, writers
must serialize). In the coproc data path, records happen from harvester
daemons, the host-stage pool's shard workers AND the coproc-tick executor
concurrently, so every record there goes through a serializing lock (the
engine's ``_stat_add`` records under ``_stats_lock``) — an unlocked record
silently LOSES samples under contention, which corrupts exactly the
latency tails the governor derives its adaptive deadlines from.

Heuristic scope (no type inference), confined to ``redpanda_tpu/coproc``
(the one subtree where several threads share the engine's histograms;
single-threaded dispatch-layer records elsewhere are the owning thread by
contract):

- HST1001: ``<histogram>.record(...)`` — a receiver whose dotted name
  mentions ``hist`` — outside any lexically-enclosing ``with`` block whose
  context manager looks like a lock (dotted name mentioning ``lock`` /
  ``mutex``).
- HST1002: the same, with the histogram looked up inline —
  ``coproc_stage_hist(...).record(...)`` / ``registry.histogram(...)
  .record(...)`` — the shape where the lock is easiest to forget because
  no histogram variable exists to "own".

A record inside a function DEFINED under a lock block does not count as
locked (the closure runs later, on whatever thread calls it), and a
``with`` that is not a lock (``tracer.span(...)``) does not serialize.
A site that is genuinely single-threaded carries a reasoned
``# pandalint: disable=HST1001 -- ...`` pragma, which doubles as the
documentation of WHY that thread owns the histogram.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.pandalint.checkers.base import (
    Checker,
    FileContext,
    RawFinding,
    dotted,
)

_LOCKISH = ("lock", "mutex")


def _is_lockish(expr: ast.expr) -> bool:
    """Does a with-item's context expression look like a serializing lock?
    Accepts names/attributes (``self._stats_lock``) and calls returning
    one (``lock()``, ``self._lock.acquire_timeout(...)``)."""
    if isinstance(expr, ast.Call):
        expr = expr.func
    name = dotted(expr).lower()
    return any(part in name for part in _LOCKISH)


def _hist_receiver(call: ast.Call) -> tuple[str, str] | None:
    """(rule, receiver description) when this is a histogram .record()."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "record"):
        return None
    recv = f.value
    if isinstance(recv, ast.Call):
        name = dotted(recv.func)
        if "hist" in name.lower():
            return "HST1002", f"{name}(...)"
        return None
    name = dotted(recv)
    if name and "hist" in name.lower():
        return "HST1001", name
    return None


class HdrRecordChecker(Checker):
    name = "hdr-record"
    rules = {
        "HST1001": "histogram .record() in threaded coproc code outside a "
                   "serializing lock (HdrHist read-modify-write contract)",
        "HST1002": "inline histogram lookup .record() (coproc_stage_hist/"
                   "registry.histogram) outside a serializing lock",
    }

    def check(self, ctx: FileContext) -> Iterator[RawFinding]:
        yield from self._walk(ctx.tree.body, locked=False)

    def _walk(self, body, locked: bool) -> Iterator[RawFinding]:
        for node in body:
            yield from self._visit(node, locked)

    def _visit(self, node: ast.AST, locked: bool) -> Iterator[RawFinding]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def under a lock block runs LATER, on whatever
            # thread calls it: the lock is not held there
            yield from self._walk(node.body, locked=False)
            return
        if isinstance(node, ast.Lambda):
            yield from self._visit(node.body, locked=False)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            has_lock = any(_is_lockish(item.context_expr) for item in node.items)
            for item in node.items:  # the context exprs evaluate unlocked
                yield from self._visit(item.context_expr, locked)
            yield from self._walk(node.body, locked or has_lock)
            return
        if isinstance(node, ast.Call):
            hit = _hist_receiver(node)
            if hit is not None and not locked:
                rule, recv = hit
                yield RawFinding(
                    rule,
                    node.lineno,
                    node.col_offset,
                    f"{recv}.record() without a serializing lock: "
                    f"HdrHist.record is a read-modify-write and coproc "
                    f"records race across harvester/pool/executor threads "
                    f"— hold the owning lock (the engine records under "
                    f"_stats_lock)",
                )
        for child in ast.iter_child_nodes(node):
            yield from self._visit(child, locked)
