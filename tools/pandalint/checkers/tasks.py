"""Task hygiene: no lost asyncio tasks, no un-awaited coroutines.

``asyncio.create_task`` only holds a weak reference to the task: a task
whose handle is dropped can be garbage-collected mid-flight, and its
exceptions vanish into the void (the reference's ssx::spawn_with_gate
exists for exactly this). Retain handles in a set (add_done_callback to
discard) and cancel them on shutdown. A bare un-awaited coroutine call
never runs at all — Python only warns at GC time.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.pandalint.checkers.base import Checker, FileContext, RawFinding, dotted


def _is_create_task(node: ast.Call) -> bool:
    name = dotted(node.func)
    if name.endswith(".create_task") or name == "create_task":
        return True
    # asyncio.get_running_loop().create_task(...) / get_event_loop() chains
    f = node.func
    return isinstance(f, ast.Attribute) and f.attr == "create_task"


def _ensure_future(node: ast.Call) -> bool:
    return dotted(node.func).endswith("ensure_future")


class TaskHygieneChecker(Checker):
    name = "task-hygiene"
    rules = {
        "TSK301": "asyncio.create_task result dropped (lost task)",
        "TSK302": "coroutine called but not awaited",
    }

    def check(self, ctx: FileContext) -> Iterator[RawFinding]:
        # --- TSK301: bare-statement create_task ------------------------------
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
                call = node.value
                if _is_create_task(call) or _ensure_future(call):
                    yield RawFinding(
                        "TSK301",
                        node.lineno,
                        node.col_offset,
                        "create_task() handle dropped: the task can be "
                        "GC'd mid-flight and its exceptions are lost; retain "
                        "it (set + add_done_callback) and cancel on shutdown",
                    )

        # --- TSK302: bare-statement calls to known-async functions ----------
        mod_async = {
            n.name
            for n in ctx.tree.body
            if isinstance(n, ast.AsyncFunctionDef)
        }
        class_async: list[set[str]] = []

        checker = self
        findings: list[RawFinding] = []

        class V(ast.NodeVisitor):
            def visit_ClassDef(self, node: ast.ClassDef) -> None:
                class_async.append(
                    {
                        m.name
                        for m in node.body
                        if isinstance(m, ast.AsyncFunctionDef)
                    }
                )
                self.generic_visit(node)
                class_async.pop()

            def visit_Expr(self, node: ast.Expr) -> None:
                if not isinstance(node.value, ast.Call):
                    return
                f = node.value.func
                target = None
                if isinstance(f, ast.Name) and f.id in mod_async:
                    target = f.id
                elif (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self"
                    and any(f.attr in s for s in class_async)
                ):
                    target = "self." + f.attr
                if target is not None:
                    findings.append(
                        RawFinding(
                            "TSK302",
                            node.lineno,
                            node.col_offset,
                            f"{target}() is a coroutine function but the "
                            f"call is not awaited — it never runs",
                        )
                    )

        V().visit(ctx.tree)
        yield from findings
