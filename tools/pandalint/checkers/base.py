"""Checker interface + per-file analysis context."""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterator

from tools.pandalint.jitgraph import JitGraph


@dataclass
class RawFinding:
    """A violation before suppression/scope handling."""

    rule: str
    line: int
    col: int
    message: str


@dataclass
class FileContext:
    """Everything a checker may need about one parsed file."""

    relpath: str                 # posix, relative to the lint root
    tree: ast.Module
    source: str
    lines: list[str] = field(default_factory=list)
    _jit: JitGraph | None = None

    def __post_init__(self) -> None:
        if not self.lines:
            self.lines = self.source.splitlines()

    @property
    def jit(self) -> JitGraph:
        if self._jit is None:
            self._jit = JitGraph(self.tree)
        return self._jit

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


class Checker:
    """Base class: subclasses set `name` + `rules` and implement check().

    A checker with ``program_level = True`` implements ``check_program``
    instead: it sees the WHOLE parsed program (every file of the run) plus
    the affinity/lock analyses, and yields ``(relpath, RawFinding)`` pairs
    — the executor-affinity and lock-order rules reason about spawn sites
    in one file and the functions they execute in another."""

    name: str = ""
    rules: dict[str, str] = {}
    program_level: bool = False

    def check(self, ctx: FileContext) -> Iterator[RawFinding]:  # pragma: no cover
        raise NotImplementedError

    def check_program(self, program, locks):  # pragma: no cover
        """program: affinity.Program; locks: lockgraph.LockGraph.
        Yields (relpath, RawFinding)."""
        raise NotImplementedError


def dotted(node: ast.expr) -> str:
    """'a.b.c' for Name/Attribute chains, '' otherwise."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def enclosing_async_functions(tree: ast.Module) -> list[ast.AsyncFunctionDef]:
    return [n for n in ast.walk(tree) if isinstance(n, ast.AsyncFunctionDef)]


def walk_in_function(fn) -> Iterator[ast.AST]:
    """Walk a function body WITHOUT descending into nested function defs —
    a nested sync helper has its own execution context."""
    stack: list[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))
