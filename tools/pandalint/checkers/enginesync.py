"""Engine-loop purity: no device→host syncs on the coproc tick/harvest path.

The engine's data path is asynchronous by design: dispatch issues the
launch and ``copy_to_host_async``, and the ONE sanctioned place to pay the
D2H round trip is the dedicated harvester thread (engine._harvest_loop runs
on its own daemon thread, off the event loop). A ``np.asarray(device_arr)``
/ ``.tobytes()`` / ``block_until_ready()`` inside an ``async def`` — or
inside a tick/harvest-named loop body — blocks the broker's event loop for
a full link round trip (~70 ms over a tunneled link): raft heartbeats stop,
elections fire, and the launch pipeline serializes.

Heuristic scope (no type inference): any call of these shapes inside an
``async def``, or inside a function whose name mentions tick/harvest, in
the checker's scope (defaults to ``redpanda_tpu/coproc``). A sanctioned
sync — e.g. the harvester thread's own fetch — carries a reasoned
``# pandalint: disable=ENG502 -- ...`` pragma, which doubles as
documentation of WHY that sync is allowed to exist.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.pandalint.checkers.base import (
    Checker,
    FileContext,
    RawFinding,
    dotted,
    walk_in_function,
)

_NUMPY_ALIASES = {"np", "numpy"}
_SYNC_ATTRS = {"block_until_ready"}
_LOOPY_NAMES = ("tick", "harvest")


def _is_engine_loop(fn: ast.AST) -> bool:
    if isinstance(fn, ast.AsyncFunctionDef):
        return True
    if isinstance(fn, ast.FunctionDef):
        name = fn.name.lower()
        return any(part in name for part in _LOOPY_NAMES)
    return False


class EngineSyncChecker(Checker):
    name = "engine-sync"
    rules = {
        "ENG501": ".tobytes() host materialization on the engine tick/harvest path",
        "ENG502": "np.asarray() device fetch on the engine tick/harvest path",
        "ENG503": "block_until_ready()/jax.device_get() on the engine tick/harvest path",
    }

    def check(self, ctx: FileContext) -> Iterator[RawFinding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _is_engine_loop(fn):
                continue
            where = (
                "async" if isinstance(fn, ast.AsyncFunctionDef) else "loop"
            )
            for node in walk_in_function(fn):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                # any .tobytes() form: ndarray.tobytes accepts a positional
                # order argument, so arg count must not gate the rule
                if isinstance(f, ast.Attribute) and f.attr == "tobytes":
                    yield RawFinding(
                        "ENG501",
                        node.lineno,
                        node.col_offset,
                        f".tobytes() in {where} {fn.name}() forces a host "
                        f"sync on the engine loop; materialize on the "
                        f"harvester thread",
                    )
                    continue
                name = dotted(f)
                root, _, tail = name.partition(".")
                if root in _NUMPY_ALIASES and tail == "asarray":
                    yield RawFinding(
                        "ENG502",
                        node.lineno,
                        node.col_offset,
                        f"{name}() in {where} {fn.name}() pays the D2H round "
                        f"trip on the engine loop; use copy_to_host_async + "
                        f"the harvester thread",
                    )
                elif (
                    isinstance(f, ast.Attribute) and f.attr in _SYNC_ATTRS
                ) or name == "jax.device_get":
                    yield RawFinding(
                        "ENG503",
                        node.lineno,
                        node.col_offset,
                        f"{name or f.attr}() in {where} {fn.name}() blocks "
                        f"on the device from the engine loop",
                    )
