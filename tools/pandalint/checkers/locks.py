"""Lock discipline: no network RPC awaited while holding an asyncio.Lock.

An ``asyncio.Lock`` is cheap to hold across pure computation, but awaiting
a network round trip inside one couples every waiter to the peer's latency
tail: a slow or dead peer turns a microsecond critical section into a
seconds-long convoy, and with the coproc tick deadline / raft election
timers above it, into timeouts and re-elections. The reference avoids the
shape structurally (seastar's ``with_semaphore`` bodies are local;
cross-core work goes through ``submit_to`` WITHOUT holding the unit) —
here the contract is convention, enforced by this checker.

Remedies: copy what you need under the lock, drop it, then call; or make
the RPC idempotent and tolerate the duplicate; or — when serializing the
RPC is genuinely the point (create-once mutexes, state-machine ordering) —
suppress with a reason, which doubles as documentation of why that convoy
is acceptable.

Heuristic scope (no type inference): inside an ``async with`` whose
context expression mentions lock/mutex, an awaited call whose method name
is a known RPC entry point:

- LCK701 — transport-level sends: ``.send(...)``, ``.send_request(...)``,
  ``.invoke_on(...)`` (rpc/transport.py and invoke_on-style peer calls).
- LCK702 — dispatch-layer RPC: ``.topic_op(...)``, ``.replicate(...)``,
  ``.pull_initial(...)`` (controller dispatch / raft replication fan-out).
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.pandalint.checkers.base import (
    Checker,
    FileContext,
    RawFinding,
    dotted,
)

_SEND_METHODS = {"send", "send_request", "invoke_on"}
_DISPATCH_METHODS = {"topic_op", "replicate", "pull_initial"}


def _holds_lock(node: ast.AST) -> bool:
    if not isinstance(node, (ast.With, ast.AsyncWith)):
        return False
    for item in node.items:
        ctx = item.context_expr
        if isinstance(ctx, ast.Call):
            ctx = ctx.func
        name = dotted(ctx).lower()
        if "lock" in name or "mutex" in name:
            return True
    return False


def _method_name(call: ast.expr) -> str:
    if isinstance(call, ast.Call) and isinstance(call.func, ast.Attribute):
        return call.func.attr
    return ""


class LockRpcChecker(Checker):
    name = "lock-rpc"
    rules = {
        "LCK701": "transport send/invoke_on awaited while holding an asyncio.Lock",
        "LCK702": "dispatch-layer RPC (topic_op/replicate/...) awaited while holding an asyncio.Lock",
    }

    def check(self, ctx: FileContext) -> Iterator[RawFinding]:
        for fn in ast.walk(ctx.tree):
            if isinstance(fn, ast.AsyncFunctionDef):
                yield from self._walk(fn, fn.name, locked=False)

    def _walk(
        self, node: ast.AST, fn_name: str, locked: bool
    ) -> Iterator[RawFinding]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs run in their own (unlocked) context
            child_locked = locked or _holds_lock(child)
            if (
                isinstance(child, ast.Await)
                and child_locked
                and isinstance(child.value, ast.Call)
            ):
                method = _method_name(child.value)
                rule = (
                    "LCK701" if method in _SEND_METHODS
                    else "LCK702" if method in _DISPATCH_METHODS
                    else None
                )
                if rule is not None:
                    yield RawFinding(
                        rule,
                        child.lineno,
                        child.col_offset,
                        f"{fn_name}() awaits the network RPC .{method}() "
                        f"while holding an asyncio.Lock; every waiter "
                        f"inherits the peer's latency tail — drop the lock "
                        f"before the call, or suppress with the reason the "
                        f"serialization is intended",
                    )
            yield from self._walk(child, fn_name, child_locked)
