"""Hot-path purity: no host syncs inside jit-reachable code.

Everything reachable from a ``@jax.jit`` / ``jax.vmap`` / ``shard_map`` root
executes under trace. A ``float()`` / ``int()`` / ``bool()`` / ``.item()``
on a traced value raises at best and forces a device->host sync at worst; a
literal ``np.*`` call runs on the host at trace time (silently baking a
constant into the program, or serializing the dispatch pipeline when fed a
concrete array between launches); data-dependent Python ``if``/``while`` on
traced arguments either raises a ConcretizationTypeError or — through
``static_argnums`` misuse — triggers a silent retrace per distinct value.

Static conversions belong OUTSIDE the traced function (hoist to closure
setup); if a flagged call is genuinely trace-time-static, suppress with a
reason saying so.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.pandalint.checkers.base import Checker, FileContext, RawFinding, dotted
from tools.pandalint.jitgraph import expr_tainted

_CASTS = {"float", "int", "bool", "complex"}
_DEVICE_SYNCS = {"device_get", "block_until_ready"}
_NUMPY_ALIASES = {"np", "numpy"}


def _is_const(node: ast.expr) -> bool:
    """Literal-ish expressions that can't be tracers."""
    return isinstance(node, (ast.Constant, ast.JoinedStr)) or (
        isinstance(node, ast.UnaryOp) and isinstance(node.operand, ast.Constant)
    )


class HotPathSyncChecker(Checker):
    name = "hotpath-sync"
    rules = {
        "HPS201": "float()/int()/bool() conversion inside jit-reachable code",
        "HPS202": ".item() host materialization inside jit-reachable code",
        "HPS203": "jax.device_get/block_until_ready inside jit-reachable code",
    }

    def check(self, ctx: FileContext) -> Iterator[RawFinding]:
        for info in ctx.jit.reachable_functions():
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Name) and f.id in _CASTS:
                    if node.args and _is_const(node.args[0]):
                        continue
                    yield RawFinding(
                        "HPS201",
                        node.lineno,
                        node.col_offset,
                        f"{f.id}() inside jit-reachable {info.name}() "
                        f"materializes on host; hoist the conversion out of "
                        f"the traced function",
                    )
                elif isinstance(f, ast.Attribute) and f.attr == "item" and not node.args:
                    yield RawFinding(
                        "HPS202",
                        node.lineno,
                        node.col_offset,
                        f".item() inside jit-reachable {info.name}() forces a "
                        f"device sync",
                    )
                elif isinstance(f, ast.Attribute) and f.attr in _DEVICE_SYNCS:
                    root = dotted(f).split(".", 1)[0]
                    if root == "jax":
                        yield RawFinding(
                            "HPS203",
                            node.lineno,
                            node.col_offset,
                            f"jax.{f.attr}() inside jit-reachable "
                            f"{info.name}() serializes the dispatch pipeline",
                        )


class HotPathNumpyChecker(Checker):
    name = "hotpath-numpy"
    rules = {
        "HPN211": "numpy call inside jit-reachable code",
    }

    def check(self, ctx: FileContext) -> Iterator[RawFinding]:
        for info in ctx.jit.reachable_functions():
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted(node.func)
                root = name.split(".", 1)[0]
                if root in _NUMPY_ALIASES and "." in name:
                    yield RawFinding(
                        "HPN211",
                        node.lineno,
                        node.col_offset,
                        f"{name}() inside jit-reachable {info.name}() runs on "
                        f"host at trace time; use jnp or hoist to closure "
                        f"setup",
                    )


class HotPathControlChecker(Checker):
    name = "hotpath-control"
    rules = {
        "HPC221": "data-dependent Python if/while on traced values",
    }

    def check(self, ctx: FileContext) -> Iterator[RawFinding]:
        for info in ctx.jit.reachable_functions():
            if not info.tainted_params:
                continue
            tainted = ctx.jit._tainted_names(info)
            for node in ast.walk(info.node):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                if expr_tainted(node.test, tainted):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    yield RawFinding(
                        "HPC221",
                        node.lineno,
                        node.col_offset,
                        f"data-dependent `{kind}` on traced values in "
                        f"{info.name}(); use jnp.where/lax.cond/lax.while_loop",
                    )
