"""Reactor discipline: no blocking calls lexically inside ``async def``.

The broker runs one asyncio loop per shard (the seastar-reactor analogue);
one blocking call inside a coroutine stalls every connection, raft timer
and fetch long-poll on that shard. Offload with ``asyncio.to_thread`` /
``loop.run_in_executor``, use the async primitive (``asyncio.sleep``,
``asyncio.create_subprocess_exec``, stream APIs), or — for genuinely
startup-only paths — suppress with a reason.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.pandalint.checkers.base import (
    Checker,
    FileContext,
    RawFinding,
    dotted,
    enclosing_async_functions,
    walk_in_function,
)

_SLEEPS = {"time.sleep"}
_SUBPROCESS = {
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.Popen",
    "os.system",
    "os.spawnl",
    "os.spawnv",
    "os.popen",
}
# sync filesystem entry points; os.path.* predicates are cheap metadata and
# deliberately not flagged
_FILE_IO = {
    "open",
    "io.open",
    "os.listdir",
    "os.walk",
    "os.scandir",
    "os.replace",
    "os.rename",
    "os.remove",
    "os.unlink",
    "os.makedirs",
    "os.rmdir",
    "shutil.copy",
    "shutil.copyfile",
    "shutil.copytree",
    "shutil.rmtree",
    "shutil.move",
}
_SOCKET = {
    "socket.create_connection",
    "socket.socket",
    "socket.getaddrinfo",
    "socket.gethostbyname",
}


class ReactorChecker(Checker):
    name = "reactor"
    rules = {
        "RCT101": "blocking time.sleep() inside async def",
        "RCT102": "blocking subprocess/os-exec call inside async def",
        "RCT103": "synchronous file I/O inside async def",
        "RCT104": "synchronous socket call inside async def",
    }

    def check(self, ctx: FileContext) -> Iterator[RawFinding]:
        for fn in enclosing_async_functions(ctx.tree):
            for node in walk_in_function(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted(node.func)
                rule = None
                if name in _SLEEPS:
                    rule = "RCT101"
                elif name in _SUBPROCESS:
                    rule = "RCT102"
                elif name in _FILE_IO:
                    rule = "RCT103"
                elif name in _SOCKET:
                    rule = "RCT104"
                if rule is None:
                    continue
                yield RawFinding(
                    rule,
                    node.lineno,
                    node.col_offset,
                    f"{name}() blocks the event loop inside async "
                    f"{fn.name}(); use the asyncio primitive or "
                    f"asyncio.to_thread",
                )
