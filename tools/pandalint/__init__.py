"""pandalint — AST invariant checker for reactor-stall and tracer-leak bugs.

The reference Redpanda enforces reactor discipline socially (a blocking call
inside a seastar task stalls the whole shard); this reproduction has the same
bug class twice over — a blocking call inside ``async def`` stalls the broker
event loop, and a host sync inside a jitted op silently serializes the TPU
hot path. pandalint makes both mechanical:

- **reactor discipline** (RCT1xx): no ``time.sleep`` / ``subprocess`` / sync
  file or socket I/O lexically inside ``async def`` bodies in the broker,
  raft, rpc, storage, cloud_storage and archival layers.
- **hot-path purity** (HPS2xx / HPN2xx / HPC2xx): inside functions reachable
  from a ``@jax.jit`` / ``partial(jax.jit, ...)`` / ``jax.vmap`` /
  ``shard_map`` root, no host materialization (``float()`` / ``int()`` /
  ``bool()`` / ``.item()`` / ``jax.device_get``), no ``np.*`` calls, and no
  data-dependent Python ``if`` / ``while`` on traced arguments.
- **task hygiene** (TSK3xx): no dropped ``asyncio.create_task`` handles and
  no un-awaited coroutine calls (lost-task races).
- **iobuf copy discipline** (IOB4xx): no ``bytes(...)`` materialization of
  buffer views inside per-record loops or as throwaway hash/CRC arguments.

Usage::

    python -m tools.pandalint redpanda_tpu/ --strict
    pandalint redpanda_tpu/ --format json
    pandalint redpanda_tpu/ --write-baseline pandalint-baseline.json
    pandalint redpanda_tpu/ --strict --baseline pandalint-baseline.json

Suppress a finding on its line (a reason is mandatory)::

    time.sleep(0.1)  # pandalint: disable=RCT101 -- fault injection only

See tools/pandalint/README.md for the full rule catalog.
"""

from tools.pandalint.finding import Finding
from tools.pandalint.engine import LintEngine, lint_paths

__version__ = "0.1.0"

__all__ = ["Finding", "LintEngine", "lint_paths", "__version__"]
