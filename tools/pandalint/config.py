"""Scoping configuration.

Each checker applies to a set of package subtrees; files under the package
root that match no scope are skipped for that checker, while files OUTSIDE
the package root (e.g. test fixtures) always get every checker — fixtures
must be lintable without ceremony.

Defaults can be overridden from ``pyproject.toml``::

    [tool.pandalint]
    package_root = "redpanda_tpu"

    [tool.pandalint.scopes]
    reactor = ["redpanda_tpu/kafka", "redpanda_tpu/raft"]
"""

from __future__ import annotations

from dataclasses import dataclass, field

# Every checker runs package-wide by default: the hot-path rules are
# already gated on jit reachability and the reactor rules on `async def`,
# so broad scope adds no noise — and a violation injected ANYWHERE under
# the package must fail the gate. Narrow via [tool.pandalint.scopes] when
# a subtree genuinely owns a different contract (e.g. blocking CLIs).
DEFAULT_SCOPES: dict[str, tuple[str, ...]] = {
    "reactor": (),        # empty scope = the whole package
    "hotpath-sync": (),
    "hotpath-numpy": (),
    "hotpath-control": (),
    "task-hygiene": (),
    "iobuf-copy": (),
    # Engine-loop host-sync rules reason about the coproc data path's
    # async-dispatch contract; np.asarray on host data is perfectly normal
    # elsewhere in the package, so this checker does NOT run package-wide.
    "engine-sync": ("redpanda_tpu/coproc",),
    # Cross-shard isolation reasons about the host-stage pool's worker
    # naming convention (*_shard vs *_sharded), which only the coproc data
    # path follows; SHD603's queue-internals rule is cheap but the naming
    # heuristic would be noise elsewhere.
    "cross-shard": ("redpanda_tpu/coproc",),
    # Locks + network RPC can meet anywhere in the broker (raft, cluster,
    # coproc, kafka server), so the await-under-lock rule is package-wide.
    "lock-rpc": (),
    # Disguised blocking sleeps can stall any shard's reactor; package-wide
    # (the checker itself exempts the finjector, whose deliberate blocking
    # sleeps ARE the injected fault).
    "sleep-async": (),
    # note_failure classification is a coproc fault-domain contract
    # (coproc/faults.py); a broad catch elsewhere in the broker has no
    # classifier to report to, so the rule would only breed pragmas there.
    "bare-except": ("redpanda_tpu/coproc",),
    # HdrHist.record serialization is a threaded-coproc contract: the
    # engine's histograms are shared by harvester/pool/executor threads.
    # Dispatch-layer records elsewhere run on the owning event loop by
    # construction, so package-wide the rule would only breed pragmas.
    "hdr-record": ("redpanda_tpu/coproc",),
    # The pandaraces whole-program analyses: execution contexts (spawn
    # sites) and locks exist across the whole broker — the affinity call
    # graph is built package-wide regardless, and a race injected in any
    # subtree must fail the gate.
    "races": (),
    "deadlocks": (),
    # Resource lifecycle (RSL16xx) pairs acquires with releases over the
    # same whole-program graph; leaks can hide in any subtree that touches
    # a budget account, gate, arena, pool, or engine — package-wide.
    "lifecycle": (),
    # Raw pair-timing routed through probes/trace/pulse is a HOT-PATH
    # contract (the pandapulse flight recorder's single-source-of-timing
    # invariant); elsewhere (cli, tools, archival) a throwaway timer is
    # legitimate and the rule would only breed pragmas.
    "perf-timing": (
        "redpanda_tpu/coproc", "redpanda_tpu/kafka", "redpanda_tpu/rpc",
        "redpanda_tpu/raft",
    ),
    # Series-name single-sourcing (probes.py) is a hot-path contract; the
    # observability plane and resource_mgmt own their registrations (the
    # registration site IS the single source there), so the rule would
    # only breed pragmas outside the data-path packages.
    "metrics-hygiene": (
        "redpanda_tpu/coproc", "redpanda_tpu/kafka", "redpanda_tpu/rpc",
        "redpanda_tpu/raft", "redpanda_tpu/storage",
    ),
}

DEFAULT_PACKAGE_ROOT = "redpanda_tpu"


@dataclass
class Config:
    package_root: str = DEFAULT_PACKAGE_ROOT
    scopes: dict[str, tuple[str, ...]] = field(
        default_factory=lambda: dict(DEFAULT_SCOPES)
    )

    def checker_applies(self, checker_name: str, relpath: str) -> bool:
        rel = relpath.replace("\\", "/")
        root = self.package_root.rstrip("/") + "/"
        if not (rel.startswith(root) or rel == self.package_root):
            return True  # outside the package (fixtures, tools): lint fully
        scope = self.scopes.get(checker_name, ())
        if not scope:
            return True
        return any(rel.startswith(p.rstrip("/") + "/") or rel == p for p in scope)

    @classmethod
    def load(cls, pyproject_path: str | None = None) -> "Config":
        cfg = cls()
        if pyproject_path is None:
            return cfg
        try:
            import tomllib
        except ImportError:  # Python < 3.11
            try:
                import tomli as tomllib  # type: ignore[no-redef]
            except ImportError:
                return cfg
        try:
            with open(pyproject_path, "rb") as f:
                data = tomllib.load(f)
        except (OSError, ValueError):
            return cfg
        section = data.get("tool", {}).get("pandalint", {})
        if "package_root" in section:
            cfg.package_root = str(section["package_root"])
        for name, paths in section.get("scopes", {}).items():
            cfg.scopes[name] = tuple(str(p) for p in paths)
        return cfg
