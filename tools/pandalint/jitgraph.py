"""Module-local jit reachability + light taint analysis.

The hot-path checkers need to know, per file:

1. which functions are **jit roots** — decorated with ``@jax.jit`` /
   ``@jit`` / ``@partial(jax.jit, ...)`` / ``@jax.vmap`` / ``@jax.pmap``,
   or passed by name to ``jax.jit(f)`` / ``jax.vmap(f)`` /
   ``shard_map(f, ...)``;
2. which functions are **reachable** from a root through module-local
   calls (bare-name calls and ``self.method`` calls within a class) —
   everything a root calls executes under trace, so the purity rules apply
   to the whole reachable set;
3. which names inside a reachable function are **traced** — seeded from the
   root's parameters and propagated through call arguments and simple
   assignments, with ``.shape`` / ``.ndim`` / ``.dtype`` / ``len()``
   explicitly laundering taint (static under jit).

The analysis is intentionally module-local and name-based: cross-module
reachability would need import resolution for marginal gain, and a false
edge is worse than a missed one for a lint gate people must keep green.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

_JIT_ATTRS = {"jit", "vmap", "pmap"}
_WRAPPER_CALLS = {"jit", "vmap", "pmap", "shard_map"}
_SHAPE_ATTRS = {"shape", "ndim", "dtype", "size"}
_TAINT_LAUNDER_CALLS = {"len", "range", "enumerate", "isinstance", "type"}


def _dotted(node: ast.expr) -> str:
    """'jax.jit' for Attribute/Name chains, '' otherwise."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jit_marker(node: ast.expr) -> bool:
    """True for jax.jit / jit / jax.vmap / partial(jax.jit, ...) etc."""
    if isinstance(node, ast.Call):
        name = _dotted(node.func)
        if name.rsplit(".", 1)[-1] == "partial":
            return any(_is_jit_marker(a) for a in node.args)
        return name.rsplit(".", 1)[-1] in _WRAPPER_CALLS
    name = _dotted(node)
    return name.rsplit(".", 1)[-1] in _JIT_ATTRS


@dataclass
class FuncInfo:
    node: ast.FunctionDef | ast.AsyncFunctionDef
    cls: str | None                      # enclosing class name, if a method
    is_root: bool = False
    reachable: bool = False
    tainted_params: set[str] = field(default_factory=set)

    @property
    def name(self) -> str:
        return self.node.name

    def param_names(self) -> list[str]:
        a = self.node.args
        names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names


class JitGraph:
    """Reachability + taint facts for one parsed module."""

    def __init__(self, tree: ast.Module):
        self.funcs: dict[int, FuncInfo] = {}        # id(node) -> info
        self._by_name: dict[str, list[FuncInfo]] = {}
        self._collect(tree)
        self._mark_roots(tree)
        self._propagate()

    # ------------------------------------------------------------ collection
    def _collect(self, tree: ast.Module) -> None:
        stack: list[str | None] = [None]

        graph = self

        class V(ast.NodeVisitor):
            def visit_ClassDef(self, node: ast.ClassDef) -> None:
                stack.append(node.name)
                self.generic_visit(node)
                stack.pop()

            def _func(self, node) -> None:
                info = FuncInfo(node, cls=stack[-1])
                graph.funcs[id(node)] = info
                graph._by_name.setdefault(node.name, []).append(info)
                if any(_is_jit_marker(d) for d in node.decorator_list):
                    info.is_root = True
                self.generic_visit(node)

            visit_FunctionDef = _func
            visit_AsyncFunctionDef = _func

        V().visit(tree)

    def _mark_roots(self, tree: ast.Module) -> None:
        # jax.jit(fn) / shard_map(_local, ...) style roots: the function is
        # passed by name as the first positional argument
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func).rsplit(".", 1)[-1]
            if name not in _WRAPPER_CALLS or not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Name):
                for info in self._by_name.get(arg.id, []):
                    info.is_root = True

    # ------------------------------------------------------------ reachability
    def _callees(self, info: FuncInfo) -> list[tuple[FuncInfo, ast.Call]]:
        out = []
        for sub in ast.walk(info.node):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            if isinstance(f, ast.Name):
                for cand in self._by_name.get(f.id, []):
                    out.append((cand, sub))
            elif (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id in ("self", "cls")
                and info.cls is not None
            ):
                for cand in self._by_name.get(f.attr, []):
                    if cand.cls == info.cls:
                        out.append((cand, sub))
        return out

    def _propagate(self) -> None:
        work = []
        for info in self.funcs.values():
            if info.is_root:
                info.reachable = True
                info.tainted_params.update(info.param_names())
                work.append(info)
        # termination: a function re-enters the worklist only when its
        # reachable flag or tainted_params grew, both monotonic
        while work:
            info = work.pop()
            tainted = self._tainted_names(info)
            for callee, call in self._callees(info):
                changed = not callee.reachable
                callee.reachable = True
                params = callee.param_names()
                for i, arg in enumerate(call.args):
                    if i < len(params) and expr_tainted(arg, tainted):
                        if params[i] not in callee.tainted_params:
                            callee.tainted_params.add(params[i])
                            changed = True
                for kw in call.keywords:
                    if kw.arg and kw.arg in params and expr_tainted(kw.value, tainted):
                        if kw.arg not in callee.tainted_params:
                            callee.tainted_params.add(kw.arg)
                            changed = True
                if changed:
                    work.append(callee)

    # ------------------------------------------------------------ taint
    def _tainted_names(self, info: FuncInfo) -> set[str]:
        """Forward pass over the function body: names carrying traced data."""
        tainted = set(info.tainted_params)
        # two passes to settle simple use-before-reassign chains
        for _ in range(2):
            for stmt in ast.walk(info.node):
                if isinstance(stmt, ast.Assign):
                    src = expr_tainted(stmt.value, tainted)
                    for tgt in stmt.targets:
                        for n in ast.walk(tgt):
                            if isinstance(n, ast.Name):
                                if src:
                                    tainted.add(n.id)
                                else:
                                    tainted.discard(n.id)
                elif isinstance(stmt, ast.AugAssign) and isinstance(stmt.target, ast.Name):
                    if expr_tainted(stmt.value, tainted):
                        tainted.add(stmt.target.id)
        return tainted

    # ------------------------------------------------------------ queries
    def info_for(self, node) -> FuncInfo | None:
        return self.funcs.get(id(node))

    def reachable_functions(self) -> list[FuncInfo]:
        return [f for f in self.funcs.values() if f.reachable]


def expr_tainted(node: ast.expr, tainted: set[str]) -> bool:
    """Does the expression mention a tainted name, modulo laundering?

    ``x.shape`` / ``x.ndim`` / ``x.dtype`` / ``x.size`` and ``len(x)`` are
    static under jit and do not propagate taint.
    """
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _SHAPE_ATTRS:
            continue
        if isinstance(sub, ast.Call):
            fname = _dotted(sub.func).rsplit(".", 1)[-1]
            if fname in _TAINT_LAUNDER_CALLS:
                continue
        if isinstance(sub, ast.Name) and sub.id in tainted:
            # laundered when it only appears under .shape/.len — approximate:
            # check the direct parent chain instead of re-walking; cheap
            # version: treat any bare mention as tainted unless the WHOLE
            # expression is a shape access
            if not _under_launder(node, sub):
                return True
    return False


def _under_launder(root: ast.expr, target: ast.Name) -> bool:
    """True when `target` only feeds shape/len-style static accessors."""

    class P(ast.NodeVisitor):
        def __init__(self):
            self.hit = False

        def visit_Attribute(self, node: ast.Attribute) -> None:
            if node.attr in _SHAPE_ATTRS and any(
                sub is target for sub in ast.walk(node.value)
            ):
                return  # laundered subtree: don't descend
            self.generic_visit(node)

        def visit_Call(self, node: ast.Call) -> None:
            fname = _dotted(node.func).rsplit(".", 1)[-1]
            if fname in _TAINT_LAUNDER_CALLS and any(
                sub is target for a in node.args for sub in ast.walk(a)
            ):
                return
            self.generic_visit(node)

        def visit_Name(self, node: ast.Name) -> None:
            if node is target:
                self.hit = True

    p = P()
    p.visit(root)
    return not p.hit
