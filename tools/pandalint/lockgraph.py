"""Lock identity, locksets, and the global acquisition-order graph.

Shared by the RAC11xx lockset checker and the DLK12xx lock-order checker,
and by the runtime cross-check (``tests`` assert the ``coproc_lockwatch``
recorder's observed acquisition edges are a SUBGRAPH of the static graph
built here — the analyzer is itself verified, not just shipped).

**Lock identity.** Python has no lock declarations, so identity is
name-based and canonicalized:

- ``self._X`` inside class ``C`` → ``"C._X"``;
- a bare module-global ``NAME`` → ``"<module>.NAME"`` (resolved through
  ``from``-imports to the defining module);
- ``obj._X`` on anything else → the set of classes known to OWN a lock
  attribute ``_X`` (discovered from ``self._X = threading.Lock()``-style
  assignments); a unique owner resolves cleanly, several owners make the
  site *ambiguous* (kept for the superset graph, excluded from cycle
  reporting — a false cycle from smeared identity would breed pragmas).

A ``with`` item counts as a lock acquisition when its context
expression's dotted name mentions ``lock``/``mutex`` (the same lexical
heuristic the LCK checker uses; ``.acquire()``-style manual acquisition
is out of scope and noted in the README).

**Locksets.** The effective lockset at a node is the lexical ``with``
stack PLUS the function's *entry lockset*: the intersection over every
resolved call site of the locks held there (a fixpoint, so
``framed() -> _materialize_locked() -> _mat_columnar()`` chains carry
``_Launch._lock`` all the way down — the engine documents such contracts
as "caller holds self._lock", and the analysis must see through them).
Entry locksets only shrink as more call sites are discovered; an
unresolvable caller is treated as absent, which UNDER-approximates held
locks and therefore over-reports races — the safe direction for a gate.

**Acquisition graph.** Edges ``held -> acquired`` from every lexical
nesting, plus ``held -> may_acquire(callee)`` for every call made while
holding a lock, where ``may_acquire`` is the transitive closure of locks
a function can take (fixpoint over the call graph). Cycles in the
unambiguous sub-graph are DLK1201 findings; the full (superset) graph is
what the lockwatch runtime edges are checked against.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from tools.pandalint.affinity import (
    AMBIG_LIMIT,
    Program,
    ProgFunc,
    dotted,
    modbase,
)

_LOCK_CTORS = {"Lock", "RLock"}


def _is_lock_name(name: str) -> bool:
    low = name.lower()
    return "lock" in low or "mutex" in low


def _lock_ctor_in(value: ast.expr) -> bool:
    """Does this assigned value construct a lock (possibly wrapped, e.g.
    ``lockwatch.wrap(threading.Lock(), ...)``)?"""
    for sub in ast.walk(value):
        if isinstance(sub, ast.Call):
            name = dotted(sub.func).rsplit(".", 1)[-1]
            if name in _LOCK_CTORS:
                return True
    return False


@dataclass(frozen=True)
class LockRef:
    """One syntactic lock reference, canonicalized.

    ``ids`` lists every candidate canonical identity; ``ambiguous`` is
    True when the owner could not be pinned to exactly one."""

    ids: tuple[str, ...]
    ambiguous: bool

    @property
    def primary(self) -> str:
        return self.ids[0]


@dataclass
class Acquisition:
    """One ``with <lock>`` acquisition site."""

    ref: LockRef
    fn: ProgFunc
    lineno: int
    col: int
    held: frozenset[str] = frozenset()      # lexical only; finalized later


@dataclass
class EdgeSite:
    relpath: str
    lineno: int
    col: int
    ambiguous: bool
    via: str  # "nesting" | "call:<callee>"


class LockGraph:
    """Locksets + acquisition graph for one affinity Program."""

    def __init__(self, program: Program):
        self.program = program
        # attr name -> class names owning a lock attribute of that name
        self._lock_attr_owners: dict[str, set[str]] = {}
        # modkey -> module-global lock names
        self._module_locks: dict[str, set[str]] = {}
        self._collect_lock_defs()

        # per-function: lexical acquisitions, lexical held-at for every
        # Attribute/Call node, and the calls made (node -> held set)
        self.acquisitions: list[Acquisition] = []
        self._held_lex: dict[int, frozenset[str]] = {}   # id(node) -> held
        self._call_sites: dict[int, list[tuple[ProgFunc, ast.Call]]] = {}
        self._fn_calls: dict[int, list[ast.Call]] = {}
        for fn in program.funcs.values():
            self._walk_function(fn)
        self.entry: dict[int, frozenset[str]] = {}
        self._solve_entry_locksets()
        self.may_acquire: dict[int, frozenset[str]] = {}
        self._solve_may_acquire()
        # (src, dst) -> [EdgeSite, ...]
        self.edges: dict[tuple[str, str], list[EdgeSite]] = {}
        self._build_edges()

    # ------------------------------------------------------------ definitions
    def _collect_lock_defs(self) -> None:
        from tools.pandalint.affinity import modkey_of

        for relpath, tree in self.program.modules:
            modkey = modkey_of(relpath)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Assign):
                    continue
                if not _lock_ctor_in(node.value):
                    continue
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        info = self._enclosing_class(tree, node)
                        if info:
                            self._lock_attr_owners.setdefault(
                                tgt.attr, set()
                            ).add(info)
                    elif isinstance(tgt, ast.Name):
                        # class-body assignment = a class-level lock (its
                        # canonical id is Class.attr); module-level = a
                        # module-global lock
                        cls = self._enclosing_class(tree, node)
                        if cls:
                            self._lock_attr_owners.setdefault(
                                tgt.id, set()
                            ).add(cls)
                        else:
                            self._module_locks.setdefault(
                                modkey, set()
                            ).add(tgt.id)

    @staticmethod
    def _enclosing_class(tree: ast.Module, target: ast.AST) -> str | None:
        """Class lexically containing ``target`` (one linear scan per
        lookup; lock definitions are rare)."""
        found: list[str] = []

        def visit(node: ast.AST, cls: str | None) -> bool:
            if node is target:
                if cls:
                    found.append(cls)
                return True
            for child in ast.iter_child_nodes(node):
                nxt = node.name if isinstance(node, ast.ClassDef) else cls
                if visit(child, nxt):
                    return True
            return False

        visit(tree, None)
        return found[0] if found else None

    # ------------------------------------------------------------ identity
    def lock_ref(self, fn: ProgFunc, ctx: ast.expr) -> LockRef | None:
        """Canonical identity for a ``with`` context expression, or None
        when it does not look like a lock."""
        if isinstance(ctx, ast.Call):
            ctx = ctx.func
        chain = dotted(ctx)
        if not chain or not _is_lock_name(chain):
            return None
        parts = chain.split(".")
        if parts[0] in ("self", "cls") and len(parts) == 2 and fn.cls:
            return LockRef((f"{fn.cls}.{parts[1]}",), False)
        if parts[0] in self.program.classes and len(parts) == 2:
            # ClassName._lock: a class-level lock addressed explicitly
            # (TpuEngine._columnar_probe_lock style)
            return LockRef((f"{parts[0]}.{parts[1]}",), False)
        if len(parts) == 1:
            name = parts[0]
            alias = self.program._aliases.get(fn.modkey, {}).get(name)
            if alias is not None and alias[0] == "symbol":
                return LockRef((f"{modbase(alias[1])}.{name}",), False)
            return LockRef((f"{modbase(fn.modkey)}.{name}",), False)
        # module-attr lock: `engine_mod._mask_claim_lock`
        alias = self.program._aliases.get(fn.modkey, {}).get(parts[0])
        if alias is not None and alias[0] == "module" and len(parts) == 2:
            return LockRef((f"{modbase(alias[1])}.{parts[1]}",), False)
        attr = parts[-1]
        owners = sorted(self._lock_attr_owners.get(attr, ()))
        if len(owners) == 1:
            return LockRef((f"{owners[0]}.{attr}",), False)
        if owners:
            return LockRef(
                tuple(f"{o}.{attr}" for o in owners), True
            )
        return LockRef((f"?.{attr}",), True)

    # ------------------------------------------------------------ per function
    def _walk_function(self, fn: ProgFunc) -> None:
        calls: list[ast.Call] = []

        def walk(node: ast.AST, held: frozenset[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    continue  # separate ProgFuncs with their own walks
                child_held = held
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    for item in child.items:
                        ref = self.lock_ref(fn, item.context_expr)
                        if ref is None:
                            continue
                        self.acquisitions.append(
                            Acquisition(
                                ref,
                                fn,
                                item.context_expr.lineno,
                                item.context_expr.col_offset,
                                child_held,
                            )
                        )
                        # ambiguous holds contribute ALL candidates: a
                        # lockset that MIGHT hold the lock is treated as
                        # holding it (fewer false race positives)
                        child_held = child_held | frozenset(ref.ids)
                if isinstance(child, (ast.Attribute, ast.Call)):
                    self._held_lex[id(child)] = child_held
                    if isinstance(child, ast.Call):
                        calls.append(child)
                walk(child, child_held)

        walk(fn.node, frozenset())
        self._fn_calls[id(fn.node)] = calls
        for call in calls:
            callees, _amb = self.program.resolve_call(fn, call)
            for callee in callees:
                self._call_sites.setdefault(id(callee.node), []).append(
                    (fn, call)
                )

    # ------------------------------------------------------------ fixpoints
    def _solve_entry_locksets(self) -> None:
        """entry(f) = ∩ over call sites of (entry(caller) ∪ held at the
        call), seeded EMPTY and grown to the least fixpoint. The ∅ seed
        matters: a ⊤ seed leaves call cycles with no outside caller
        pinned at "every lock held", exploding the edge graph; the least
        fixpoint UNDER-approximates held locks instead, which over-reports
        races — the safe direction for a lint gate."""
        entry: dict[int, frozenset[str]] = {
            id(fn.node): frozenset() for fn in self.program.funcs.values()
        }
        changed = True
        while changed:
            changed = False
            for fn in self.program.funcs.values():
                sites = self._call_sites.get(id(fn.node))
                if not sites:
                    continue
                acc: frozenset[str] | None = None
                for caller, call in sites:
                    held = self._held_lex.get(id(call), frozenset())
                    held = held | entry[id(caller.node)]
                    acc = held if acc is None else (acc & held)
                acc = acc or frozenset()
                if acc != entry[id(fn.node)]:
                    entry[id(fn.node)] = acc
                    changed = True
        self.entry = entry

    def _may_fixpoint(self, unique_methods: bool, clean_lex: bool):
        """may(f) = locks acquired lexically in f ∪ may(every callee).

        Two instantiations: the CLEAN closure (unique call resolution,
        unambiguous lock identities only) feeds cycle detection — one
        smeared ``.read()`` resolving into an unrelated class would
        manufacture false deadlock cycles; the FULL closure (candidates
        up to AMBIG_LIMIT, every lock id) makes the graph a SUPERSET,
        which is what the runtime lockwatch subgraph check needs."""
        lex: dict[int, set[str]] = {
            id(fn.node): set() for fn in self.program.funcs.values()
        }
        for acq in self.acquisitions:
            if clean_lex and acq.ref.ambiguous:
                continue
            lex[id(acq.fn.node)].update(acq.ref.ids)
        may = {k: frozenset(v) for k, v in lex.items()}
        callee_map: dict[int, list[ProgFunc]] = {}
        for fn in self.program.funcs.values():
            outs: list[ProgFunc] = []
            for call in self._fn_calls.get(id(fn.node), []):
                cands, amb = self.program.resolve_call(
                    fn, call, unique_methods=unique_methods
                )
                if unique_methods and amb:
                    continue
                outs.extend(cands)
            callee_map[id(fn.node)] = outs
        changed = True
        while changed:
            changed = False
            for fn in self.program.funcs.values():
                cur = may[id(fn.node)]
                nxt = set(cur)
                for callee in callee_map[id(fn.node)]:
                    nxt |= may.get(id(callee.node), frozenset())
                if len(nxt) != len(cur):
                    may[id(fn.node)] = frozenset(nxt)
                    changed = True
        return may, callee_map

    def _solve_may_acquire(self) -> None:
        self.may_clean, self._clean_callees = self._may_fixpoint(
            unique_methods=True, clean_lex=True
        )
        self.may_acquire, self._full_callees = self._may_fixpoint(
            unique_methods=False, clean_lex=False
        )

    # ------------------------------------------------------------ graph
    def held_at(self, fn: ProgFunc, node: ast.AST) -> frozenset[str]:
        """Effective lockset at a node: lexical stack + entry lockset."""
        return self._held_lex.get(id(node), frozenset()) | self.entry.get(
            id(fn.node), frozenset()
        )

    def calls_of(self, fn: ProgFunc) -> list[ast.Call]:
        """The call nodes in fn's own body (no nested defs) — the public
        face of the per-function call index checkers iterate."""
        return self._fn_calls.get(id(fn.node), [])

    def _add_edge(
        self, src: str, dst: str, site: EdgeSite
    ) -> None:
        if src == dst:
            # a self-edge from name-smearing is noise; REAL reentrant
            # acquisition of a non-reentrant lock is out of scope here
            # (the runtime lockwatch would deadlock on it immediately)
            return
        self.edges.setdefault((src, dst), []).append(site)

    def _build_edges(self) -> None:
        # clean edges first (cycle detection trusts only these), then the
        # full superset extras flagged ambiguous (subgraph cross-check)
        for acq in self.acquisitions:
            held = acq.held | self.entry.get(id(acq.fn.node), frozenset())
            for h in held:
                for lid in acq.ref.ids:
                    self._add_edge(
                        h,
                        lid,
                        EdgeSite(
                            acq.fn.relpath,
                            acq.lineno,
                            acq.col,
                            acq.ref.ambiguous,
                            "nesting",
                        ),
                    )
        for fn in self.program.funcs.values():
            for call in self._fn_calls.get(id(fn.node), []):
                held = self.held_at(fn, call)
                if not held:
                    continue
                clean, amb = self.program.resolve_call(
                    fn, call, unique_methods=True
                )
                full, _ = self.program.resolve_call(
                    fn, call, unique_methods=False
                )
                passes = []
                if not amb:
                    passes.append((clean, self.may_clean, False))
                passes.append((full, self.may_acquire, True))
                for callees, may, ambiguous in passes:
                    for callee in callees:
                        for lid in may.get(id(callee.node), frozenset()):
                            for h in held:
                                self._add_edge(
                                    h,
                                    lid,
                                    EdgeSite(
                                        fn.relpath,
                                        call.lineno,
                                        call.col_offset,
                                        ambiguous or lid.startswith("?."),
                                        f"call:{callee.qualname}",
                                    ),
                                )

    def edge_set(self) -> set[tuple[str, str]]:
        """Every (src, dst) in the superset graph — what the runtime
        lockwatch edge set must be a subgraph of."""
        return set(self.edges)

    def unambiguous_edges(self) -> dict[tuple[str, str], EdgeSite]:
        out: dict[tuple[str, str], EdgeSite] = {}
        for key, sites in self.edges.items():
            clean = [s for s in sites if not s.ambiguous]
            if clean and not any(i.startswith("?.") for i in key):
                out[key] = clean[0]
        return out

    def cycle_edges(self) -> list[tuple[str, str, EdgeSite, list[str]]]:
        """Edges participating in a cycle of the unambiguous graph, each
        with one witness cycle (src -> ... -> src) for the message."""
        clean = self.unambiguous_edges()
        adj: dict[str, set[str]] = {}
        for (src, dst) in clean:
            adj.setdefault(src, set()).add(dst)
        # SCCs via iterative Tarjan
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on_stack: set[str] = set()
        stack: list[str] = []
        sccs: dict[str, int] = {}
        counter = [0]
        scc_id = [0]

        def strongconnect(v0: str) -> None:
            work = [(v0, iter(sorted(adj.get(v0, ()))))]
            index[v0] = low[v0] = counter[0]
            counter[0] += 1
            stack.append(v0)
            on_stack.add(v0)
            while work:
                v, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(sorted(adj.get(w, ())))))
                        advanced = True
                        break
                    if w in on_stack:
                        low[v] = min(low[v], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    pv = work[-1][0]
                    low[pv] = min(low[pv], low[v])
                if low[v] == index[v]:
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        sccs[w] = scc_id[0]
                        if w == v:
                            break
                    scc_id[0] += 1

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)

        scc_size: dict[int, int] = {}
        for v, s in sccs.items():
            scc_size[s] = scc_size.get(s, 0) + 1

        out = []
        for (src, dst), site in sorted(clean.items()):
            if (
                src in sccs
                and dst in sccs
                and sccs[src] == sccs[dst]
                and scc_size[sccs[src]] > 1
            ):
                out.append((src, dst, site, self._witness(adj, dst, src)))
        return out

    @staticmethod
    def _witness(
        adj: dict[str, set[str]], start: str, goal: str
    ) -> list[str]:
        """Shortest path start -> goal (BFS) to render one cycle."""
        if start == goal:
            return [start]
        seen = {start}
        frontier = [[start]]
        while frontier:
            nxt = []
            for path in frontier:
                for w in sorted(adj.get(path[-1], ())):
                    if w == goal:
                        return path + [w]
                    if w not in seen:
                        seen.add(w)
                        nxt.append(path + [w])
            frontier = nxt
        return [start, goal]
