"""Finding record + stable fingerprints for baseline comparison."""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str           # e.g. "RCT101"
    path: str           # repo-relative posix path
    line: int           # 1-based
    col: int            # 0-based
    message: str
    checker: str        # checker name, e.g. "reactor"
    source_line: str = ""       # stripped text of the offending line
    suppressed: bool = False    # a disable pragma with a reason covers it
    suppress_reason: str = ""

    def fingerprint(self) -> str:
        """Stable identity for baselines: rule + file + normalized source
        text (NOT the line number, so unrelated edits above the finding
        don't invalidate the baseline)."""
        h = hashlib.sha256()
        h.update(self.rule.encode())
        h.update(b"\0")
        h.update(self.path.encode())
        h.update(b"\0")
        h.update(" ".join(self.source_line.split()).encode())
        return h.hexdigest()[:16]

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "checker": self.checker,
            # round-trips through the engine cache; fingerprints derive
            # from it, so dropping it would reshuffle baselines
            "source_line": self.source_line,
            "suppressed": self.suppressed,
            "suppress_reason": self.suppress_reason,
            "fingerprint": self.fingerprint(),
        }

    def render(self) -> str:
        tag = " (suppressed: %s)" % self.suppress_reason if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}{tag}"


@dataclass
class FileReport:
    """All findings for one file, plus parse status."""

    path: str
    findings: list[Finding] = field(default_factory=list)
    parse_error: str | None = None
