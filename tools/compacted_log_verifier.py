"""Black-box compacted-log verifier.

Parity with the reference's tests/java/compacted-log-verifier (invoked
from the ducktape compaction suite): a standalone tool that records a
compacted topic's expected per-key state over the Kafka API before/while
compaction runs, then verifies after compaction that

1. every key's LATEST value survived and is still the last value for the
   key (compaction must never lose the newest write),
2. every surviving value for a key appeared in the recorded history in
   the same order (nothing resurrected or reordered),
3. per-partition offsets remain strictly increasing.

Usage:
  # produce a known keyed workload (ground truth, like the Java verifier's
  # producer side) and store the expected state:
  python tools/compacted_log_verifier.py produce --brokers h:p --topic t \
      --state /tmp/state.json --keys 5 --count 60
  # or observe an existing topic's current state:
  python tools/compacted_log_verifier.py record --brokers h:p --topic t \
      --state /tmp/state.json
  # after compaction, check the invariants:
  python tools/compacted_log_verifier.py verify --brokers h:p --topic t \
      --state /tmp/state.json
Exit code 0 = invariants hold, 1 = violation (details on stderr).

The topic must contain only the recorded workload (use a dedicated topic,
as the reference's verifier does): any surviving key or partition absent
from the recorded state is reported as resurrected data.
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from redpanda_tpu.cli.rpk import _parse_brokers as _parse  # noqa: E402


def _h(b: bytes | None) -> str:
    return "null" if b is None else hashlib.sha1(b).hexdigest()


async def _consume_all(brokers: list[tuple[str, int]], topic: str):
    """[(partition, offset, key_hash, value_hash)] over the full topic."""
    from redpanda_tpu.kafka.client.client import KafkaClient

    c = await KafkaClient(brokers).connect()
    try:
        await c.refresh_metadata([topic])
        parts = sorted(p for (t, p) in c._leaders if t == topic)
        out = []
        for p in parts:
            offset = 0
            while True:
                batches, hwm = await c.fetch(topic, p, offset, max_wait_ms=50)
                for b in batches:
                    for r in b.records():
                        out.append(
                            (p, b.header.base_offset + r.offset_delta,
                             _h(r.key), _h(r.value))
                        )
                if batches:
                    offset = batches[-1].last_offset + 1
                if offset >= hwm:
                    break
        return out
    finally:
        await c.close()


def _per_key(records):
    """{partition: {key_hash: [value_hash in offset order]}}"""
    keyed: dict[int, dict[str, list[str]]] = {}
    for p, _off, kh, vh in records:
        keyed.setdefault(p, {}).setdefault(kh, []).append(vh)
    return keyed


async def cmd_produce(args) -> int:
    """Produce `count` acked keyed records cycling over `keys` keys into
    partition 0, and store exactly what was acked as the expected state —
    immune to compaction racing the observation."""
    from redpanda_tpu.kafka.client.client import KafkaClient

    c = await KafkaClient(_parse(args.brokers)).connect()
    history: dict[str, list[str]] = {}
    try:
        for i in range(args.count):
            key = b"key-%d" % (i % args.keys)
            value = b"val-%08d" % i
            await c.produce(args.topic, 0, [(key, value)], acks=-1)
            history.setdefault(_h(key), []).append(_h(value))
    finally:
        await c.close()
    with open(args.state, "w") as f:
        json.dump({"topic": args.topic, "partitions": {"0": history}}, f)
    print(f"produced {args.count} records over {args.keys} keys -> {args.state}")
    return 0


async def cmd_record(args) -> int:
    records = await _consume_all(_parse(args.brokers), args.topic)
    keyed = _per_key(records)
    state = {
        "topic": args.topic,
        "partitions": {
            str(p): {kh: vals for kh, vals in by_key.items()}
            for p, by_key in keyed.items()
        },
    }
    with open(args.state, "w") as f:
        json.dump(state, f)
    n_keys = sum(len(v) for v in keyed.values())
    print(f"recorded {len(records)} records, {n_keys} keys -> {args.state}")
    return 0


def _is_subsequence(needle: list[str], hay: list[str]) -> bool:
    it = iter(hay)
    return all(any(x == h for h in it) for x in needle)


async def cmd_verify(args) -> int:
    with open(args.state) as f:
        state = json.load(f)
    if state["topic"] != args.topic:
        print(f"state is for topic {state['topic']!r}", file=sys.stderr)
        return 1
    records = await _consume_all(_parse(args.brokers), args.topic)
    got = _per_key(records)
    errors: list[str] = []

    # offsets strictly increasing per partition
    last_off: dict[int, int] = {}
    for p, off, _kh, _vh in records:
        if off <= last_off.get(p, -1):
            errors.append(f"p{p}: offset {off} not increasing")
        last_off[p] = off

    for p_str, expected in state["partitions"].items():
        p = int(p_str)
        surviving = got.get(p, {})
        for kh, history in expected.items():
            latest = history[-1]
            chain = surviving.get(kh)
            if chain is None:
                errors.append(f"p{p} key {kh[:12]}: lost entirely")
            elif chain[-1] != latest:
                errors.append(
                    f"p{p} key {kh[:12]}: latest value changed "
                    f"({chain[-1][:12]} != {latest[:12]})"
                )
            elif not _is_subsequence(chain, history):
                errors.append(
                    f"p{p} key {kh[:12]}: surviving values resurrected or "
                    f"reordered vs recorded history"
                )
        # reverse direction: anything in the topic that was never recorded
        # is resurrected data (a key fully removed before `record`, or
        # records duplicated into the partition)
        for kh in surviving:
            if kh not in expected:
                errors.append(f"p{p} key {kh[:12]}: resurrected (never recorded)")
    for p in got:
        if str(p) not in state["partitions"]:
            errors.append(f"p{p}: partition has data but was never recorded")
    if errors:
        for e in errors:
            print(f"VIOLATION: {e}", file=sys.stderr)
        return 1
    n_keys = sum(len(v) for v in got.values())
    print(f"verified {len(records)} surviving records, {n_keys} keys: OK")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    for name in ("produce", "record", "verify"):
        sp = sub.add_parser(name)
        sp.add_argument("--brokers", required=True)
        sp.add_argument("--topic", required=True)
        sp.add_argument("--state", required=True)
        if name == "produce":
            sp.add_argument("--keys", type=int, default=8)
            sp.add_argument("--count", type=int, default=200)
    args = p.parse_args(argv)
    table = {"produce": cmd_produce, "record": cmd_record, "verify": cmd_verify}
    return asyncio.run(table[args.cmd](args))


if __name__ == "__main__":
    sys.exit(main())
