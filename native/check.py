#!/usr/bin/env python3
"""Native parity micro-tests (`make check`): the fast gate that a freshly
built libredpanda_native.so computes what it claims, on THIS host's
dispatch path (hardware CRC if the CPU has SSE4.2, AVX2 classification if
it has AVX2 — the same binary must be correct on every tier).

Pure ctypes + stdlib: runnable straight from native/ with no package
import, so a cross-compiled or prebuilt .so can be checked in isolation.
"""

import ctypes
import os
import struct
import sys
import zlib

HERE = os.path.dirname(os.path.abspath(__file__))
SO = os.path.join(HERE, "libredpanda_native.so")


def crc32c_ref(data: bytes) -> int:
    """Bit-reflected CRC-32C (Castagnoli) reference, table-free."""
    crc = 0xFFFFFFFF
    for byte in data:
        crc ^= byte
        for _ in range(8):
            crc = (crc >> 1) ^ (0x82F63B78 if crc & 1 else 0)
    return crc ^ 0xFFFFFFFF


def zigzag(v: int) -> bytes:
    u = (v << 1) ^ (v >> 63) if v < 0 else v << 1
    out = bytearray()
    while u >= 0x80:
        out.append((u & 0x7F) | 0x80)
        u >>= 7
    out.append(u)
    return bytes(out)


def frame_record(seq: int, value: bytes | None) -> bytes:
    body = bytearray(b"\x00")
    body += zigzag(0)
    body += zigzag(seq)
    body += zigzag(-1)
    if value is None:
        body += zigzag(-1)
    else:
        body += zigzag(len(value)) + value
    body += zigzag(0)
    return zigzag(len(body)) + bytes(body)


def main() -> int:
    dll = ctypes.CDLL(SO)
    failures = 0

    def check(name, ok):
        nonlocal failures
        print(f"  {'ok' if ok else 'FAIL'}  {name}")
        if not ok:
            failures += 1

    # ---- CRC: runtime-dispatched implementation vs pure-python reference
    dll.rp_crc32c.restype = ctypes.c_uint32
    dll.rp_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    for blob in (b"", b"a", b"123456789", bytes(range(256)) * 9 + b"tail"):
        got = dll.rp_crc32c(blob, len(blob))
        check(f"crc32c len={len(blob)}", got == crc32c_ref(blob))

    # ---- structural vs scalar parse: identical span tables
    has2 = hasattr(dll, "rp_explode_find2")
    check("rp_explode_find2 symbol present", has2)
    if not has2:
        return 1
    values = [
        b'{"level":"error","code":5,"msg":"hello"}',
        b'{"a":"esc\\"aped","level":"in\\\\fo","code":-3.5e2,"msg":""}',
        b'{"level":"x","nested":{"a":[1,{"q":"}"}]},"code":true,"msg":null}',
        "{\"level\":\"ünïcødé\",\"code\":42,\"msg\":\"πλ\"}".encode(),
        b'{"msg":"' + b"\\\"" * 64 + b'","level":"error","code":9}',
        b'{"truncated":"unterminated',
        b"[1,2]",
        b"{}",
        None,  # null value
    ]
    payload = b"".join(
        frame_record(i, v) for i, v in enumerate(values)
    )
    n = len(values)
    paths = [b"level", b"code", b"msg", b"nested"]
    blob = b"".join(paths)
    k = len(paths)
    path_off = (ctypes.c_int32 * k)(*[
        sum(len(p) for p in paths[:i]) for i in range(k)
    ])
    path_len = (ctypes.c_int32 * k)(*[len(p) for p in paths])

    def tables():
        return (
            (ctypes.c_int64 * n)(), (ctypes.c_int32 * n)(),
            (ctypes.c_int8 * (n * k))(), (ctypes.c_int64 * (n * k))(),
            (ctypes.c_int64 * (n * k))(),
        )

    p_len = (ctypes.c_int32 * 1)(len(payload))
    counts = (ctypes.c_int32 * 1)(n)
    p_off = (ctypes.c_int64 * 1)(0)
    a = tables()
    dll.rp_explode_find.restype = ctypes.c_int64
    got = dll.rp_explode_find(
        payload, p_off, p_len, counts, 1, blob, path_off, path_len, k,
        a[0], a[1], a[2], a[3], a[4],
    )
    check("scalar parse count", got == n)
    ptrs = (ctypes.c_char_p * 1)(payload)
    joined = ctypes.create_string_buffer(len(payload))
    b = tables()
    dll.rp_explode_find2.restype = ctypes.c_int64
    got2 = dll.rp_explode_find2(
        ptrs, p_len, counts, 1, joined, blob, path_off, path_len, k,
        b[0], b[1], b[2], b[3], b[4],
    )
    check("structural parse count", got2 == n)
    check("joined blob copy", joined.raw == payload)
    check("val_off parity", list(a[0]) == list(b[0]))
    check("val_len parity", list(a[1]) == list(b[1]))
    check("types parity", list(a[2]) == list(b[2]))
    span_ok = all(
        a[2][i] == 0 or (a[3][i] == b[3][i] and a[4][i] == b[4][i])
        for i in range(n * k)
    )
    check("span parity (found paths)", span_ok)

    # ---- gather framing round trip (rp_frame_gather)
    if hasattr(dll, "rp_frame_gather"):
        dll.rp_frame_gather.restype = ctypes.c_int64
        vals = [v for v in values if v is not None]
        src = b"".join(vals)
        offs, lens, pos = [], [], 0
        for v in vals:
            offs.append(pos)
            lens.append(len(v))
            pos += len(v)
        nn = len(vals)
        keep = (ctypes.c_uint8 * nn)(*([1] * nn))
        dst = ctypes.create_string_buffer(len(src) + 16 * nn + 16)
        kept = ctypes.c_int32()
        ln = dll.rp_frame_gather(
            src, (ctypes.c_int64 * nn)(*offs), (ctypes.c_int32 * nn)(*lens),
            keep, nn, dst, ctypes.byref(kept),
        )
        expect = b"".join(frame_record(i, v) for i, v in enumerate(vals))
        check("frame_gather bytes", dst.raw[:ln] == expect and kept.value == nn)

    print(("PASS" if failures == 0 else f"FAIL ({failures})"))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
