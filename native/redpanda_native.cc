// redpanda_tpu native runtime helpers.
//
// TPU-native equivalent of the reference's native byte-plane: CRC32C
// (hardware-accelerated, mirroring its use of google/crc32c), xxhash-free
// framing helpers, and the hot host-side loop that packs variable-length
// records into fixed-shape [P, B, R] device staging buffers (and unpacks
// them back), which feeds the XLA data plane through the bridge.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <cstddef>
#include <cmath>
#include <cstdlib>

#if defined(__x86_64__)
// x86intrin.h + per-function target attributes instead of a global -msse4.2:
// the .so must never carry SSE4.2 instructions outside runtime-dispatched
// functions, or a prebuilt binary SIGILLs on pre-Nehalem hosts. SSE2 is
// part of the x86_64 ABI baseline and is safe to use unguarded.
#include <emmintrin.h>
#include <x86intrin.h>
#define HAVE_X86_64 1
#endif

extern "C" {

// ---------------------------------------------------------------- crc32c
static uint32_t crc_table[8][256];
static bool crc_table_init_done = false;

static void crc_table_init() {
  if (crc_table_init_done) return;
  const uint32_t poly = 0x82F63B78u;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c >> 1) ^ ((c & 1) ? poly : 0);
    crc_table[0][i] = c;
  }
  for (int k = 1; k < 8; k++)
    for (uint32_t i = 0; i < 256; i++)
      crc_table[k][i] = crc_table[0][crc_table[k - 1][i] & 0xFF] ^
                        (crc_table[k - 1][i] >> 8);
  crc_table_init_done = true;
}

static uint32_t crc32c_sw(uint32_t crc, const uint8_t* p, size_t n) {
  crc_table_init();
  while (n >= 8) {
    crc ^= (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
           ((uint32_t)p[3] << 24);
    crc = crc_table[7][crc & 0xFF] ^ crc_table[6][(crc >> 8) & 0xFF] ^
          crc_table[5][(crc >> 16) & 0xFF] ^ crc_table[4][(crc >> 24) & 0xFF] ^
          crc_table[3][p[4]] ^ crc_table[2][p[5]] ^ crc_table[1][p[6]] ^
          crc_table[0][p[7]];
    p += 8;
    n -= 8;
  }
  while (n--) crc = crc_table[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return crc;
}

#if HAVE_X86_64
__attribute__((target("sse4.2")))
static uint32_t crc32c_hw(uint32_t crc, const uint8_t* data, size_t len) {
  const uint8_t* p = data;
  size_t n = len;
  uint64_t c = crc;
  while (n && ((uintptr_t)p & 7)) { c = _mm_crc32_u8((uint32_t)c, *p++); n--; }
  while (n >= 8) {
    c = _mm_crc32_u64(c, *(const uint64_t*)p);
    p += 8;
    n -= 8;
  }
  while (n--) c = _mm_crc32_u8((uint32_t)c, *p++);
  return (uint32_t)c;
}
#endif

// crc is internal state (pre-inverted). Returns new internal state.
// Runtime feature dispatch: the SSE4.2 CRC32 instructions live only inside
// crc32c_hw (target attribute), picked once per process when the CPU
// actually has them — the same .so runs on any x86_64 (and any other arch
// via the table path). The pointer write is idempotent, so the unlocked
// first-call race is benign.
uint32_t rp_crc32c_update(uint32_t crc, const uint8_t* data, size_t len) {
#if HAVE_X86_64
  static uint32_t (*impl)(uint32_t, const uint8_t*, size_t) = nullptr;
  uint32_t (*fn)(uint32_t, const uint8_t*, size_t) = impl;
  if (!fn) {
    fn = __builtin_cpu_supports("sse4.2") ? crc32c_hw : crc32c_sw;
    impl = fn;
  }
  return fn(crc, data, len);
#else
  return crc32c_sw(crc, data, len);
#endif
}

// Final-value convenience: init 0xFFFFFFFF, xorout 0xFFFFFFFF.
uint32_t rp_crc32c(const uint8_t* data, size_t len) {
  return rp_crc32c_update(0xFFFFFFFFu, data, len) ^ 0xFFFFFFFFu;
}

// CRC N padded rows in one call: data is [n_rows, row_stride] row-major,
// lengths[i] gives the valid prefix of row i; out[i] = final CRC value.
void rp_crc32c_many(const uint8_t* data, size_t row_stride, size_t n_rows,
                    const int32_t* lengths, uint32_t* out) {
  for (size_t i = 0; i < n_rows; i++) {
    const uint8_t* row = data + i * row_stride;
    size_t len = lengths[i] < 0 ? 0 : (size_t)lengths[i];
    if (len > row_stride) len = row_stride;
    out[i] = rp_crc32c_update(0xFFFFFFFFu, row, len) ^ 0xFFFFFFFFu;
  }
}

// ---------------------------------------------------------------- packing
// Scatter n variable-length records (concatenated in `src` at `offsets`,
// sizes `sizes`) into a zero-padded [n, row_stride] staging buffer.
// Returns number of records whose size exceeded row_stride (truncated).
int32_t rp_pack_rows(const uint8_t* src, const int64_t* offsets,
                     const int32_t* sizes, size_t n, uint8_t* dst,
                     size_t row_stride) {
  int32_t truncated = 0;
  for (size_t i = 0; i < n; i++) {
    size_t sz = sizes[i] < 0 ? 0 : (size_t)sizes[i];
    if (sz > row_stride) {
      sz = row_stride;
      truncated++;
    }
    uint8_t* row = dst + i * row_stride;
    std::memcpy(row, src + offsets[i], sz);
    if (sz < row_stride) std::memset(row + sz, 0, row_stride - sz);
  }
  return truncated;
}

// Gather rows back out into a contiguous buffer; returns total bytes.
int64_t rp_unpack_rows(const uint8_t* src, size_t row_stride,
                       const int32_t* sizes, size_t n, uint8_t* dst) {
  int64_t total = 0;
  for (size_t i = 0; i < n; i++) {
    size_t sz = sizes[i] < 0 ? 0 : (size_t)sizes[i];
    if (sz > row_stride) sz = row_stride;
    std::memcpy(dst + total, src + i * row_stride, sz);
    total += (int64_t)sz;
  }
  return total;
}

// ---------------------------------------------------------------- records
// Kafka v2 record framing: zigzag varints, LSB-group-first.
static inline int64_t zz_decode(uint64_t u) {
  return (int64_t)(u >> 1) ^ -(int64_t)(u & 1);
}

static inline const uint8_t* read_uvarint(const uint8_t* p, const uint8_t* end,
                                          uint64_t* out) {
  uint64_t result = 0;
  int shift = 0;
  while (p < end && shift <= 63) {
    uint8_t b = *p++;
    result |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = result;
      return p;
    }
    shift += 7;
  }
  return nullptr;
}

static inline uint8_t* write_zigzag(uint8_t* p, int64_t v) {
  uint64_t u = ((uint64_t)v << 1) ^ (uint64_t)(v >> 63);
  while (u >= 0x80) {
    *p++ = (uint8_t)(u | 0x80);
    u >>= 7;
  }
  *p++ = (uint8_t)u;
  return p;
}

// Parse `count` varint-framed records from a batch payload; emit each
// record's value offset/length (-1 length for null values). Returns the
// number of records parsed (== count on success).
// Walk ONE record's framing from *pp; on success advance *pp past the
// record and emit the value span (vlen -1 = null value). Shared by the
// split parse (rp_parse_record_values) and the fused explode+find — the
// framing rules must not be able to diverge between them.
static inline bool parse_one_record(const uint8_t** pp, const uint8_t* end,
                                    const uint8_t** value_out,
                                    int64_t* vlen_out) {
  const uint8_t* p = *pp;
  uint64_t u;
  p = read_uvarint(p, end, &u);
  if (!p) return false;
  int64_t body_len = zz_decode(u);
  const uint8_t* body_end = p + body_len;
  if (body_len < 0 || body_end > end) return false;
  if (p >= body_end) return false;
  p++;  // attributes
  if (!(p = read_uvarint(p, body_end, &u))) return false;  // ts delta
  if (!(p = read_uvarint(p, body_end, &u))) return false;  // offset delta
  if (!(p = read_uvarint(p, body_end, &u))) return false;  // key len
  int64_t klen = zz_decode(u);
  if (klen > 0) p += klen;
  if (p > body_end) return false;
  if (!(p = read_uvarint(p, body_end, &u))) return false;  // value len
  int64_t vlen = zz_decode(u);
  if (vlen >= 0 && p + vlen > body_end) return false;
  *value_out = p;
  *vlen_out = vlen;
  *pp = body_end;  // skip headers
  return true;
}

int32_t rp_parse_record_values(const uint8_t* payload, size_t payload_len,
                               int32_t count, int64_t* val_off,
                               int32_t* val_len) {
  const uint8_t* p = payload;
  const uint8_t* end = payload + payload_len;
  for (int32_t i = 0; i < count; i++) {
    const uint8_t* value;
    int64_t vlen;
    if (!parse_one_record(&p, end, &value, &vlen)) return i;
    val_off[i] = value - payload;
    val_len[i] = vlen < 0 ? -1 : (int32_t)vlen;
  }
  return count;
}

// Parse MANY batches' record values in one call (the engine's explode
// stage: one ctypes crossing per launch instead of one per batch).
// joined = concatenated batch payloads; for batch b, payload bytes are
// joined[payload_off[b] .. +payload_len[b]) holding counts[b] records.
// Emits val_off (absolute into joined) / val_len flattened in batch order.
// Returns the number of records parsed (== sum(counts) on success).
int64_t rp_parse_many(const uint8_t* joined, const int64_t* payload_off,
                      const int32_t* payload_len, const int32_t* counts,
                      int32_t n_batches, int64_t* val_off, int32_t* val_len) {
  int64_t k = 0;
  for (int32_t b = 0; b < n_batches; b++) {
    int32_t parsed = rp_parse_record_values(
        joined + payload_off[b], (size_t)payload_len[b], counts[b],
        val_off + k, val_len + k);
    if (parsed != counts[b]) return k + parsed;
    for (int32_t i = 0; i < counts[b]; i++) val_off[k + i] += payload_off[b];
    k += counts[b];
  }
  return k;
}

// Build a records payload from kept transform outputs: record i (where
// keep[i] != 0) becomes {attrs=0, ts_delta=0, offset_delta=seq, key=null,
// value=rows[i][:lens[i]], headers=0}. Writes payload to dst (caller sizes
// it at n * (row_stride + 16)); returns payload byte length, and the number
// of kept records via *kept_out.
int64_t rp_frame_records(const uint8_t* rows, size_t row_stride,
                         const int32_t* lens, const uint8_t* keep, int32_t n,
                         uint8_t* dst, int32_t* kept_out) {
  uint8_t* out = dst;
  int32_t seq = 0;
  uint8_t body_buf[16];
  for (int32_t i = 0; i < n; i++) {
    if (!keep[i]) continue;
    int32_t vlen = lens[i] < 0 ? 0 : lens[i];
    if ((size_t)vlen > row_stride) vlen = (int32_t)row_stride;
    // body = attrs(1) + ts_delta + offset_delta + key_len(-1) + value_len +
    //        value + header_count
    uint8_t* b = body_buf;
    *b++ = 0;                      // attributes
    b = write_zigzag(b, 0);        // timestamp delta
    b = write_zigzag(b, seq);      // offset delta
    b = write_zigzag(b, -1);       // null key
    b = write_zigzag(b, vlen);     // value length
    size_t pre_len = (size_t)(b - body_buf);
    int64_t body_len = (int64_t)pre_len + vlen + 1;  // +1 header count
    out = write_zigzag(out, body_len);
    std::memcpy(out, body_buf, pre_len);
    out += pre_len;
    std::memcpy(out, rows + (size_t)i * row_stride, vlen);
    out += vlen;
    out = write_zigzag(out, 0);    // header count
    seq++;
  }
  *kept_out = seq;
  return out - dst;
}

// Frame MANY batch ranges in one crossing (one ctypes call per LAUNCH
// instead of one per batch — the per-call Python/ctypes overhead was the
// single biggest host cost at 32-record batches). For each range r,
// records [starts[r], ends[r]) are framed contiguously into dst;
// out_off/out_len give the payload slice and out_kept the surviving
// record count per range. Returns total bytes written.
int64_t rp_frame_many(const uint8_t* rows, size_t row_stride,
                      const int32_t* lens, const uint8_t* keep,
                      const int64_t* starts, const int64_t* ends,
                      int64_t n_ranges, uint8_t* dst,
                      int64_t* out_off, int64_t* out_len,
                      int32_t* out_kept) {
  uint8_t* out = dst;
  uint8_t body_buf[16];
  for (int64_t r = 0; r < n_ranges; r++) {
    uint8_t* range_start = out;
    int32_t seq = 0;
    for (int64_t i = starts[r]; i < ends[r]; i++) {
      if (!keep[i]) continue;
      int32_t vlen = lens[i] < 0 ? 0 : lens[i];
      if ((size_t)vlen > row_stride) vlen = (int32_t)row_stride;
      uint8_t* b = body_buf;
      *b++ = 0;                      // attributes
      b = write_zigzag(b, 0);        // timestamp delta
      b = write_zigzag(b, seq);      // offset delta
      b = write_zigzag(b, -1);       // null key
      b = write_zigzag(b, vlen);     // value length
      size_t pre_len = (size_t)(b - body_buf);
      int64_t body_len = (int64_t)pre_len + vlen + 1;  // +1 header count
      out = write_zigzag(out, body_len);
      std::memcpy(out, body_buf, pre_len);
      out += pre_len;
      std::memcpy(out, rows + (size_t)i * row_stride, vlen);
      out += vlen;
      out = write_zigzag(out, 0);    // header count
      seq++;
    }
    out_off[r] = range_start - dst;
    out_len[r] = out - range_start;
    out_kept[r] = seq;
  }
  return out - dst;
}

// One record framed into the output stream: {attrs=0, ts_delta=0,
// offset_delta=seq, key=null, value=value[0:vlen], headers=0}. The ONE
// framing layout shared by the gather path (values straight out of a
// source blob) — byte-for-byte the layout rp_frame_records/rp_frame_many
// emit from padded rows, which the gather parity tests pin down.
static inline uint8_t* frame_one(uint8_t* out, const uint8_t* value,
                                 int32_t vlen, int32_t seq) {
  uint8_t body_buf[16];
  uint8_t* b = body_buf;
  *b++ = 0;                      // attributes
  b = write_zigzag(b, 0);        // timestamp delta
  b = write_zigzag(b, seq);      // offset delta
  b = write_zigzag(b, -1);       // null key
  b = write_zigzag(b, vlen);     // value length
  size_t pre_len = (size_t)(b - body_buf);
  int64_t body_len = (int64_t)pre_len + vlen + 1;  // +1 header count
  out = write_zigzag(out, body_len);
  std::memcpy(out, body_buf, pre_len);
  out += pre_len;
  std::memcpy(out, value, (size_t)vlen);
  out += vlen;
  out = write_zigzag(out, 0);    // header count
  return out;
}

// ZERO-COPY framing: build a records payload for kept records straight
// from a source blob via per-record (offset, len) columns — no padded
// [n, stride] row matrix ever exists; the one memcpy per record IS the
// framed output. lens[i] < 0 (null value) frames as an empty value,
// matching the padded path's clamp. Caller sizes dst at
// sum(max(lens,0)) + 16*n + 16; returns payload length, kept via
// *kept_out.
int64_t rp_frame_gather(const uint8_t* src, const int64_t* offsets,
                        const int32_t* lens, const uint8_t* keep, int64_t n,
                        uint8_t* dst, int32_t* kept_out) {
  uint8_t* out = dst;
  int32_t seq = 0;
  for (int64_t i = 0; i < n; i++) {
    if (!keep[i]) continue;
    int32_t vlen = lens[i] < 0 ? 0 : lens[i];
    out = frame_one(out, src + offsets[i], vlen, seq);
    seq++;
  }
  *kept_out = seq;
  return out - dst;
}

// Gather-frame MANY record ranges in one crossing (the launch-wide twin of
// rp_frame_many for the zero-copy path): for each range r, kept records
// [starts[r], ends[r]) frame contiguously into dst via rp_frame_gather
// (one range = one rp_frame_gather call, so the two symbols cannot
// diverge); out_off/out_len give the payload slice and out_kept the
// surviving count per range. Returns total bytes written.
int64_t rp_frame_many_gather(const uint8_t* src, const int64_t* offsets,
                             const int32_t* lens, const uint8_t* keep,
                             const int64_t* starts, const int64_t* ends,
                             int64_t n_ranges, uint8_t* dst,
                             int64_t* out_off, int64_t* out_len,
                             int32_t* out_kept) {
  int64_t total = 0;
  for (int64_t r = 0; r < n_ranges; r++) {
    int64_t s = starts[r];
    out_off[r] = total;
    out_len[r] = rp_frame_gather(src, offsets + s, lens + s, keep + s,
                                 ends[r] - s, dst + total, out_kept + r);
    total += out_len[r];
  }
  return total;
}

// ---------------------------------------------------------------- columnar
// JSON field extraction for the columnar pushdown path (coproc engine v2).
// The device link charges per byte (tools/link_probe.py: H2D ~15-70 MB/s,
// D2H ~3-14 MB/s over the tunnel), so the engine ships *columns* of the
// fields a compiled TransformSpec references instead of record payloads.
// This walker mirrors redpanda_tpu/ops/exprs.py json_find byte-for-byte:
// parity is tested in tests/test_exprs.py (TestNativeWalkerParity).

static inline int64_t skip_ws(const uint8_t* s, int64_t i, int64_t end) {
  while (i < end && (s[i] == ' ' || s[i] == '\t' || s[i] == '\n' || s[i] == '\r'))
    i++;
  return i;
}

static int64_t skip_string(const uint8_t* s, int64_t i, int64_t end) {
  // memchr-accelerated: jump to each '"' and check whether it is escaped
  // (odd run of preceding backslashes). Equivalent to the byte-stepping
  // Python reference (ops/exprs.py _skip_string) on every input.
  i++;  // opening quote
  while (i < end) {
    const uint8_t* q =
        (const uint8_t*)std::memchr(s + i, '"', (size_t)(end - i));
    if (!q) return end;
    int64_t qi = q - s;
    int64_t bs = qi - 1;
    while (bs >= i && s[bs] == '\\') bs--;
    if (((qi - 1 - bs) & 1) == 0) return qi + 1;  // even backslashes: closes
    i = qi + 1;
  }
  return end;
}

static int64_t skip_value(const uint8_t* s, int64_t i, int64_t end) {
  i = skip_ws(s, i, end);
  if (i >= end) return end;
  uint8_t c = s[i];
  if (c == '"') return skip_string(s, i, end);
  if (c == '{' || c == '[') {
    int depth = 0;
    while (i < end) {
      c = s[i];
      if (c == '"') {
        i = skip_string(s, i, end);
        continue;
      }
      if (c == '{' || c == '[') depth++;
      else if (c == '}' || c == ']') {
        depth--;
        if (depth == 0) return i + 1;
      }
      i++;
    }
    return end;
  }
  while (i < end && c != ',' && c != '}' && c != ']' && c != ' ' && c != '\t' &&
         c != '\n' && c != '\r') {
    i++;
    if (i < end) c = s[i];
  }
  return i;
}

// Classify the value starting at s[i]; returns the type (0 missing,
// 1 string, 2 number, 3 true, 4 false, 5 null, 6 object, 7 array) and
// fills vs/ve (string extent excludes quotes). The ONE classification
// used by rp_json_find and rp_find_multi alike.
static int32_t classify_value(const uint8_t* s, int64_t i, int64_t end,
                              int64_t* vs, int64_t* ve) {
  if (i >= end) return 0;
  uint8_t c = s[i];
  if (c == '"') {
    int64_t j = skip_string(s, i, end);
    *vs = i + 1;
    *ve = j - 1;
    return 1;
  }
  if (c == '{') {
    *vs = i;
    *ve = skip_value(s, i, end);
    return 6;
  }
  if (c == '[') {
    *vs = i;
    *ve = skip_value(s, i, end);
    return 7;
  }
  int64_t j = skip_value(s, i, end);
  *vs = i;
  *ve = j;
  int64_t tl = j - i;
  if (tl == 4 && std::memcmp(s + i, "true", 4) == 0) return 3;
  if (tl == 5 && std::memcmp(s + i, "false", 5) == 0) return 4;
  if (tl == 4 && std::memcmp(s + i, "null", 4) == 0) return 5;
  return 2;
}

// Locate dot-separated `path` in JSON object s[0:len]. Returns type
// (0 missing, 1 string, 2 number, 3 true, 4 false, 5 null, 6 object,
// 7 array) and value extent via vs/ve (string extent excludes quotes).
int32_t rp_json_find(const uint8_t* s, int64_t len, const char* path,
                     int32_t path_len, int64_t* vs, int64_t* ve) {
  int64_t i = 0, end = len;
  int32_t seg_start = 0;
  for (;;) {
    int32_t seg_end = seg_start;
    while (seg_end < path_len && path[seg_end] != '.') seg_end++;
    int32_t seg_len = seg_end - seg_start;
    const char* seg = path + seg_start;
    bool last = seg_end >= path_len;

    i = skip_ws(s, i, end);
    if (i >= end || s[i] != '{') return 0;
    i++;
    for (;;) {
      i = skip_ws(s, i, end);
      if (i >= end || s[i] == '}') return 0;
      if (s[i] != '"') return 0;  // malformed
      int64_t kstart = i + 1;
      i = skip_string(s, i, end);
      int64_t kend = i - 1;
      i = skip_ws(s, i, end);
      if (i >= end || s[i] != ':') return 0;
      i++;
      i = skip_ws(s, i, end);
      if (kend - kstart == seg_len &&
          std::memcmp(s + kstart, seg, (size_t)seg_len) == 0) {
        break;  // found this segment; i is at the value start
      }
      i = skip_value(s, i, end);
      i = skip_ws(s, i, end);
      if (i < end && s[i] == ',') i++;
    }
    if (!last) {
      seg_start = seg_end + 1;
      continue;  // descend: value must parse as an object
    }
    return classify_value(s, i, end, vs, ve);
  }
}

// Extract a string-typed field into a [n, w] byte column (zero padded) plus
// per-record raw value length (clipped to 1<<30): -1 = field missing or not
// a string. Bytes are the value's raw JSON bytes (no unescaping), truncated
// to w. Returns number of records with the field present as a string.
int64_t rp_extract_str(const uint8_t* joined, const int64_t* offsets,
                       const int32_t* sizes, int64_t n, const char* path,
                       int32_t path_len, int32_t w, uint8_t* out_bytes,
                       int32_t* out_vlen) {
  int64_t hits = 0;
  for (int64_t i = 0; i < n; i++) {
    uint8_t* dst = out_bytes + i * (int64_t)w;
    std::memset(dst, 0, (size_t)w);
    int32_t sz = sizes[i];
    if (sz <= 0) {
      out_vlen[i] = -1;
      continue;
    }
    int64_t vs, ve;
    int32_t t = rp_json_find(joined + offsets[i], sz, path, path_len, &vs, &ve);
    if (t != 1) {
      out_vlen[i] = -1;
      continue;
    }
    int64_t vlen = ve - vs;
    // a record truncated inside an unterminated string yields ve < vs;
    // clamp to an empty-but-present value (memcpy with (size_t)-1 would
    // corrupt the heap)
    if (vlen < 0) vlen = 0;
    if (vlen > (1 << 30)) vlen = 1 << 30;
    out_vlen[i] = (int32_t)vlen;
    int64_t cp = vlen < w ? vlen : w;
    std::memcpy(dst, joined + offsets[i] + vs, (size_t)cp);
    hits++;
  }
  return hits;
}

// Numeric lattice flags; keep in sync with redpanda_tpu/ops/exprs.py.
enum {
  RP_F_PRESENT = 1,
  RP_F_NUMBER = 2,
  RP_F_INT_EXACT = 4,
  RP_F_BOOL = 8,
  RP_F_NULL = 16,
};

// Shared numeric classification from a found (type, vs, ve) span —
// extract_num and gather_num MUST agree byte-for-byte (parity contract
// with the Python oracle, ops/exprs.py host_field).
static void num_from_span(const uint8_t* rec, int32_t t, int64_t vs,
                          int64_t ve, float* out_f32, int32_t* out_i32,
                          uint8_t* out_flags) {
  *out_f32 = 0.0f;
  *out_i32 = 0;
  *out_flags = 0;
  if (t == 0) return;
  if (t == 3) {  // true
    *out_f32 = 1.0f;
    *out_i32 = 1;
    *out_flags = RP_F_PRESENT | RP_F_BOOL;
  } else if (t == 4) {  // false
    *out_flags = RP_F_PRESENT | RP_F_BOOL;
  } else if (t == 5) {  // null
    *out_flags = RP_F_PRESENT | RP_F_NULL;
  } else if (t == 2) {  // number
    char buf[48];
    int64_t tl = ve - vs;
    // Restrict to decimal-number characters BEFORE strtod: strtod also
    // accepts hex (0x10) / inf / nan, which the Python oracle rejects.
    bool decimal_chars = tl > 0;
    for (int64_t k = 0; k < tl && decimal_chars; k++) {
      uint8_t c = rec[vs + k];
      decimal_chars = (c >= '0' && c <= '9') || c == '-' || c == '+' ||
                      c == '.' || c == 'e' || c == 'E';
    }
    if (decimal_chars && tl < (int64_t)sizeof(buf)) {
      std::memcpy(buf, rec + vs, (size_t)tl);
      buf[tl] = 0;
      char* endp = nullptr;
      double d = strtod(buf, &endp);
      if (endp == buf + tl) {
        *out_f32 = (float)d;
        uint8_t fl = RP_F_PRESENT | RP_F_NUMBER;
        if (std::isfinite(d) && d == (double)(int64_t)d &&
            d >= -2147483648.0 && d <= 2147483647.0) {
          fl |= RP_F_INT_EXACT;
          *out_i32 = (int32_t)d;
        }
        *out_flags = fl;
      } else {
        *out_flags = RP_F_PRESENT;  // malformed number token
      }
    } else {
      *out_flags = RP_F_PRESENT;  // token too long for exact parse
    }
  } else {  // string/object/array
    *out_flags = RP_F_PRESENT;
  }
}

// Single pass over each record's TOP-LEVEL object: span tables for k
// single-segment paths in ONE walk instead of one rp_json_find per path
// (the engine's specs typically reference 2-4 fields of the same record).
// types/vs/ve are [n, k] row-major; type 0 = missing. First occurrence of
// a duplicate key wins, matching rp_json_find's scan order.
// One record's top-level JSON walk locating all k paths; writes one row of
// the span tables. Shared by rp_find_multi (standalone pass) and
// rp_explode_find (fused framing-parse + find, cache-hot).
static void find_in_record(const uint8_t* s, int64_t end,
                           const char* paths_blob, const int32_t* path_off,
                           const int32_t* path_lens, int32_t k, int8_t* trow,
                           int64_t* vrow, int64_t* erow) {
    std::memset(trow, 0, (size_t)k);
    if (end <= 0) return;
    int64_t i = skip_ws(s, 0, end);
    if (i >= end || s[i] != '{') return;
    i++;
    int32_t found = 0;
    for (;;) {
      i = skip_ws(s, i, end);
      if (i >= end || s[i] == '}') break;
      if (s[i] != '"') break;  // malformed
      int64_t kstart = i + 1;
      i = skip_string(s, i, end);
      int64_t kend = i - 1;
      i = skip_ws(s, i, end);
      if (i >= end || s[i] != ':') break;
      i++;
      i = skip_ws(s, i, end);
      int64_t klen = kend - kstart;
      bool matched = false;
      for (int32_t p = 0; p < k; p++) {
        if (trow[p] != 0) continue;  // first occurrence wins
        if (klen == path_lens[p] &&
            std::memcmp(s + kstart, paths_blob + path_off[p],
                        (size_t)path_lens[p]) == 0) {
          int64_t vs, ve;
          int32_t t = classify_value(s, i, end, &vs, &ve);
          if (t == 0) break;
          trow[p] = (int8_t)t;
          vrow[p] = vs;
          erow[p] = ve;
          matched = true;
          found++;
          // value consumed by classification: resume after it
          i = (t == 1) ? ve + 1 : ve;
          break;
        }
      }
      if (!matched) i = skip_value(s, i, end);
      i = skip_ws(s, i, end);
      if (i < end && s[i] == ',') i++;
      if (found == k) break;  // everything located
    }
}

int64_t rp_find_multi(const uint8_t* joined, const int64_t* offsets,
                      const int32_t* sizes, int64_t n,
                      const char* paths_blob, const int32_t* path_off,
                      const int32_t* path_lens, int32_t k, int8_t* types,
                      int64_t* vs_arr, int64_t* ve_arr) {
  for (int64_t r = 0; r < n; r++) {
    find_in_record(joined + offsets[r], (int64_t)sizes[r], paths_blob,
                   path_off, path_lens, k, types + r * k, vs_arr + r * k,
                   ve_arr + r * k);
  }
  return n;
}

// Fused explode + find: parse every batch's record framing AND walk each
// record's JSON value for the k paths in the SAME pass, while the record
// bytes are cache-hot — the engine's two hottest stages in one crossing
// and one memory traversal. Outputs match rp_parse_many (val_off/val_len,
// absolute into joined) plus rp_find_multi's span tables. Returns records
// parsed (== sum(counts) on success).
int64_t rp_explode_find(const uint8_t* joined, const int64_t* payload_off,
                        const int32_t* payload_len, const int32_t* counts,
                        int32_t n_batches, const char* paths_blob,
                        const int32_t* path_off, const int32_t* path_lens,
                        int32_t k, int64_t* val_off, int32_t* val_len,
                        int8_t* types, int64_t* vs_arr, int64_t* ve_arr) {
  int64_t r = 0;
  for (int32_t b = 0; b < n_batches; b++) {
    const uint8_t* p = joined + payload_off[b];
    const uint8_t* end = p + payload_len[b];
    for (int32_t i = 0; i < counts[b]; i++, r++) {
      const uint8_t* value;
      int64_t vlen;
      if (!parse_one_record(&p, end, &value, &vlen)) return r;
      val_off[r] = value - joined;
      if (vlen < 0) {
        val_len[r] = -1;
        std::memset(types + r * k, 0, (size_t)k);
      } else {
        val_len[r] = (int32_t)vlen;
        find_in_record(value, vlen, paths_blob, path_off, path_lens, k,
                       types + r * k, vs_arr + r * k, ve_arr + r * k);
      }
    }
  }
  return r;
}

// One record's projection row off its span-table row — THE shared body of
// rp_project_rows and the fused rp_extract_cols2, so the packed layout
// and ok-mask rules cannot diverge between the staged and fused ladders.
// Byte-layout parity with ColumnarPlan.assemble_rows: int/float = 4 bytes
// LE; str = LE16 clipped length + w bytes zero-padded. *ok mirrors
// extract_projection's per-kind validity (int: PRESENT|NUMBER|INT_EXACT
// and |v| <= 999999999; float: PRESENT|NUMBER; str: present and fits w).
// descs: per field {kind(0 int, 1 float, 2 str), span col, w, out off}.
static inline void project_one_row(const uint8_t* rec, const int8_t* trow,
                                   const int64_t* vrow, const int64_t* erow,
                                   const int32_t* descs, int32_t n_fields,
                                   int32_t r_out, uint8_t* row, uint8_t* ok) {
  std::memset(row, 0, (size_t)r_out);
  uint8_t okr = 1;
  for (int32_t f = 0; f < n_fields; f++) {
    const int32_t* d = descs + f * 4;
    int32_t kind = d[0], col = d[1], w = d[2], off = d[3];
    if (kind == 2) {  // str
      if (trow[col] != 1) {
        okr = 0;  // missing / non-string: zeroed slot, record dropped
        continue;
      }
      int64_t vlen = erow[col] - vrow[col];
      if (vlen < 0) vlen = 0;  // unterminated: empty-but-present
      if (vlen > w) okr = 0;
      int32_t slen = (int32_t)(vlen < w ? vlen : w);
      row[off] = (uint8_t)(slen & 0xFF);
      row[off + 1] = (uint8_t)((slen >> 8) & 0xFF);
      std::memcpy(row + off + 2, rec + vrow[col], (size_t)slen);
    } else {
      float f32;
      int32_t i32;
      uint8_t fl;
      num_from_span(rec, trow[col], vrow[col], erow[col], &f32, &i32, &fl);
      if (kind == 0) {  // int
        const uint8_t need = RP_F_PRESENT | RP_F_NUMBER | RP_F_INT_EXACT;
        if ((fl & need) != need || i32 > 999999999 || i32 < -999999999)
          okr = 0;
        std::memcpy(row + off, &i32, 4);
      } else {  // float
        const uint8_t need = RP_F_PRESENT | RP_F_NUMBER;
        if ((fl & need) != need) okr = 0;
        std::memcpy(row + off, &f32, 4);
      }
    }
  }
  *ok = okr;
}

// Fused projection: gather every Int/Float/Str projection field straight
// from the span tables into the PACKED output rows in one pass per record
// (replaces k gather_* crossings + the numpy row assembly). One shared
// per-record body with the fused extractor: project_one_row.
int64_t rp_project_rows(const uint8_t* joined, const int64_t* offsets,
                        int64_t n, const int8_t* types, const int64_t* vs,
                        const int64_t* ve, int32_t k, const int32_t* descs,
                        int32_t n_fields, int32_t r_out, uint8_t* rows,
                        uint8_t* ok) {
  for (int64_t r = 0; r < n; r++) {
    project_one_row(joined + offsets[r], types + r * k, vs + r * k,
                    ve + r * k, descs, n_fields, r_out,
                    rows + r * (int64_t)r_out, ok + r);
  }
  return n;
}

// Gather a string column from a precomputed span table column.
void rp_gather_str(const uint8_t* joined, const int64_t* offsets, int64_t n,
                   const int8_t* types, const int64_t* vs, const int64_t* ve,
                   int32_t w, uint8_t* out_bytes, int32_t* out_vlen) {
  for (int64_t i = 0; i < n; i++) {
    uint8_t* dst = out_bytes + i * (int64_t)w;
    std::memset(dst, 0, (size_t)w);
    if (types[i] != 1) {
      out_vlen[i] = -1;
      continue;
    }
    int64_t vlen = ve[i] - vs[i];
    if (vlen < 0) vlen = 0;  // unterminated string: empty-but-present
    if (vlen > (1 << 30)) vlen = 1 << 30;
    out_vlen[i] = (int32_t)vlen;
    int64_t cp = vlen < w ? vlen : w;
    std::memcpy(dst, joined + offsets[i] + vs[i], (size_t)cp);
  }
}

// Gather a numeric column from a precomputed span table column.
void rp_gather_num(const uint8_t* joined, const int64_t* offsets, int64_t n,
                   const int8_t* types, const int64_t* vs, const int64_t* ve,
                   float* out_f32, int32_t* out_i32, uint8_t* out_flags) {
  for (int64_t i = 0; i < n; i++) {
    num_from_span(joined + offsets[i], types[i], vs[i], ve[i], out_f32 + i,
                  out_i32 + i, out_flags + i);
  }
}

// Extract a numeric/bool/null field as (f32, i32, flags) per record.
// Numbers parse as double then narrow: INT_EXACT when integral and within
// int32. Strings/objects/arrays set PRESENT only. Missing -> flags 0.
int64_t rp_extract_num(const uint8_t* joined, const int64_t* offsets,
                       const int32_t* sizes, int64_t n, const char* path,
                       int32_t path_len, float* out_f32, int32_t* out_i32,
                       uint8_t* out_flags) {
  int64_t hits = 0;
  for (int64_t i = 0; i < n; i++) {
    out_f32[i] = 0.0f;
    out_i32[i] = 0;
    out_flags[i] = 0;
    int32_t sz = sizes[i];
    if (sz <= 0) continue;
    int64_t vs, ve;
    int32_t t = rp_json_find(joined + offsets[i], sz, path, path_len, &vs, &ve);
    if (t == 0) continue;
    hits++;
    num_from_span(joined + offsets[i], t, vs, ve, out_f32 + i, out_i32 + i,
                  out_flags + i);
  }
  return hits;
}

// ------------------------------------------------------------- structural
// Two-stage structural-index parse (Langdale & Lemire, "Parsing Gigabytes
// of JSON per Second"), adapted to the engine's record shape. Stage 1 is a
// vectorized character-class scan over each record's JSON value producing
// two bitmaps (bit i = value byte i): unescaped quotes, and structural
// operators ({}[]:,) OUTSIDE strings — escape runs and string interiors
// are computed branch-free with carried word ops, and the scan is seeded
// fresh per record so inter-record framing bytes can never contaminate
// the masks. Stage 2 (find2_in_record) is byte-for-byte the scalar
// find_in_record control flow, except string skips jump straight to the
// closing-quote bit and container skips walk the operator bitmap instead
// of re-scanning bytes. rp_explode_find stays exported as the parity
// oracle and fallback (tests/test_structural_parse.py pins the matrix).

static inline uint64_t bb_eq(uint64_t x, uint64_t pat) {
  // 0x80 in each byte of x equal to the broadcast byte `pat`
  uint64_t t = x ^ pat;
  return (t - 0x0101010101010101ULL) & ~t & 0x8080808080808080ULL;
}

static inline uint64_t bb_pack(uint64_t msbs) {
  // gather the 8 byte-MSBs into the low 8 bits (movemask emulation)
  return (msbs * 0x0002040810204081ULL) >> 56;
}

#define RP_BCAST(c) ((uint64_t)0x0101010101010101ULL * (uint8_t)(c))

// Stage-1 eager classification covers ONLY quote + backslash — exactly
// what the escape and in-string masks need, so the eager scan costs two
// byte-compares per 16 bytes (memchr-class throughput). The six operator
// characters are classified LAZILY per word, only when a container skip
// actually walks them (classify_op_word below) — string-heavy records
// (the bench shape: one ~1KB string value per record) never pay for them.

#if HAVE_X86_64
static void classify2_sse2(const uint8_t* p, uint64_t* quote,
                           uint64_t* bslash) {
  uint64_t q = 0, b = 0;
  const __m128i vq = _mm_set1_epi8('"');
  const __m128i vb = _mm_set1_epi8('\\');
  for (int i = 0; i < 4; i++) {
    __m128i v = _mm_loadu_si128((const __m128i*)(p + 16 * i));
    q |= (uint64_t)(uint32_t)_mm_movemask_epi8(_mm_cmpeq_epi8(v, vq))
         << (16 * i);
    b |= (uint64_t)(uint32_t)_mm_movemask_epi8(_mm_cmpeq_epi8(v, vb))
         << (16 * i);
  }
  *quote = q;
  *bslash = b;
}

#else
static void classify2_swar(const uint8_t* p, uint64_t* quote,
                           uint64_t* bslash) {
  uint64_t q = 0, b = 0;
  for (int i = 0; i < 8; i++) {
    uint64_t x;
    std::memcpy(&x, p + 8 * i, 8);
    q |= bb_pack(bb_eq(x, RP_BCAST('"'))) << (8 * i);
    b |= bb_pack(bb_eq(x, RP_BCAST('\\'))) << (8 * i);
  }
  *quote = q;
  *bslash = b;
}
#endif

// Operator bitmap for ONE 64-byte word of the value, classified on demand
// ({}[]:, — container skips are the only consumer). Tail words pad with
// zeros so the classifier never reads past the value span.
static uint64_t classify_op_word(const uint8_t* s, int64_t w, int64_t end) {
  const uint8_t* p = s + (w << 6);
  uint8_t buf[64];
  if ((w << 6) + 64 > end) {
    std::memset(buf, 0, 64);
    std::memcpy(buf, p, (size_t)(end - (w << 6)));
    p = buf;
  }
#if HAVE_X86_64
  uint64_t o = 0;
  const __m128i c1 = _mm_set1_epi8('{'), c2 = _mm_set1_epi8('}');
  const __m128i c3 = _mm_set1_epi8('['), c4 = _mm_set1_epi8(']');
  const __m128i c5 = _mm_set1_epi8(':'), c6 = _mm_set1_epi8(',');
  for (int i = 0; i < 4; i++) {
    __m128i v = _mm_loadu_si128((const __m128i*)(p + 16 * i));
    __m128i m = _mm_or_si128(
        _mm_or_si128(_mm_cmpeq_epi8(v, c1), _mm_cmpeq_epi8(v, c2)),
        _mm_or_si128(
            _mm_or_si128(_mm_cmpeq_epi8(v, c3), _mm_cmpeq_epi8(v, c4)),
            _mm_or_si128(_mm_cmpeq_epi8(v, c5), _mm_cmpeq_epi8(v, c6))));
    o |= (uint64_t)(uint32_t)_mm_movemask_epi8(m) << (16 * i);
  }
  return o;
#else
  uint64_t o = 0;
  for (int i = 0; i < 8; i++) {
    uint64_t x;
    std::memcpy(&x, p + 8 * i, 8);
    uint64_t m = bb_eq(x, RP_BCAST('{')) | bb_eq(x, RP_BCAST('}')) |
                 bb_eq(x, RP_BCAST('[')) | bb_eq(x, RP_BCAST(']')) |
                 bb_eq(x, RP_BCAST(':')) | bb_eq(x, RP_BCAST(','));
    o |= bb_pack(m) << (8 * i);
  }
  return o;
#endif
}

static inline uint64_t prefix_xor64(uint64_t x) {
  x ^= x << 1;
  x ^= x << 2;
  x ^= x << 4;
  x ^= x << 8;
  x ^= x << 16;
  x ^= x << 32;
  return x;
}

// Characters escaped by an odd-length backslash run (simdjson's
// find_escaped_branchless); *prev carries runs across word boundaries.
// Equivalent to the scalar backward odd-count at every quote because a
// run can never cross an opening quote (the quote byte breaks it).
static inline uint64_t find_escaped(uint64_t backslash, uint64_t* prev) {
  backslash &= ~*prev;
  uint64_t follows_escape = (backslash << 1) | *prev;
  const uint64_t even_bits = 0x5555555555555555ULL;
  uint64_t odd_starts = backslash & ~even_bits & ~follows_escape;
  uint64_t seq = odd_starts + backslash;
  *prev = seq < backslash;  // carry out: an odd run reaches the word end
  uint64_t invert = seq << 1;
  return (even_bits ^ invert) & follows_escape;
}

// Stage 1 over one record value: fill qbits (unescaped quotes) and sbits
// (the string-interior mask: 1 from each opening quote through the byte
// before its closing quote). Carries reset here, per record — framing
// bytes between records can never contaminate the masks. The body is a
// macro so each dispatch variant inlines its classifier (an indirect call
// per 64-byte block costs more than the classification itself), and words
// with no quote and no backslash — the string-body common case — take a
// two-store fast path: escape state decays (the pending escape consumed a
// non-quote byte) and the string mask holds.
#define RP_BUILD_STRUCTURAL_BODY(CLASSIFY2)                                  \
  uint64_t prev_escaped = 0;                                                 \
  uint64_t in_string = 0; /* 0 or ~0: string-interior carry */               \
  int64_t nwords = (len + 63) >> 6;                                          \
  for (int64_t w = 0; w < nwords; w++) {                                     \
    const uint8_t* p = s + (w << 6);                                         \
    uint64_t q, b;                                                           \
    if ((w << 6) + 64 <= len) {                                              \
      CLASSIFY2(p, &q, &b);                                                  \
    } else {                                                                 \
      /* tail block: copy-pad to 64 zero bytes — never read past the     */  \
      /* value span (the next record's framing bytes, or the blob end)   */  \
      uint8_t buf[64];                                                       \
      std::memset(buf, 0, 64);                                               \
      std::memcpy(buf, p, (size_t)(len - (w << 6)));                         \
      CLASSIFY2(buf, &q, &b);                                                \
    }                                                                        \
    if ((q | b) == 0) {                                                      \
      prev_escaped = 0;                                                      \
      qbits[w] = 0;                                                          \
      sbits[w] = in_string;                                                  \
      continue;                                                              \
    }                                                                        \
    uint64_t esc = find_escaped(b, &prev_escaped);                           \
    q &= ~esc;                                                               \
    /* inclusive prefix XOR of quote bits: 1 from each opening quote    */   \
    /* through the byte before its closing quote — exactly where an     */   \
    /* operator byte is string content, not structure                   */   \
    uint64_t S = prefix_xor64(q) ^ in_string;                                \
    in_string = (uint64_t)(-(int64_t)(S >> 63));                             \
    qbits[w] = q;                                                            \
    sbits[w] = S;                                                            \
  }

#if HAVE_X86_64
static void build_structural_sse2(const uint8_t* s, int64_t len,
                                  uint64_t* qbits, uint64_t* sbits) {
  RP_BUILD_STRUCTURAL_BODY(classify2_sse2)
}
__attribute__((target("avx2")))
static void build_structural_avx2(const uint8_t* s, int64_t len,
                                  uint64_t* qbits, uint64_t* sbits) {
  // hand-specialized: vptest answers "any quote/backslash in these 64
  // bytes" straight from the compare vectors, so the dominant string-body
  // words never pay the movemask+shift assembly of the generic path
  const __m256i vq = _mm256_set1_epi8('"');
  const __m256i vb = _mm256_set1_epi8('\\');
  uint64_t prev_escaped = 0;
  uint64_t in_string = 0;
  int64_t nwords = (len + 63) >> 6;
  for (int64_t w = 0; w < nwords; w++) {
    const uint8_t* p = s + (w << 6);
    uint8_t buf[64];
    if ((w << 6) + 64 > len) {
      std::memset(buf, 0, 64);
      std::memcpy(buf, p, (size_t)(len - (w << 6)));
      p = buf;
    }
    __m256i v0 = _mm256_loadu_si256((const __m256i*)p);
    __m256i v1 = _mm256_loadu_si256((const __m256i*)(p + 32));
    __m256i q0 = _mm256_cmpeq_epi8(v0, vq), q1 = _mm256_cmpeq_epi8(v1, vq);
    __m256i b0 = _mm256_cmpeq_epi8(v0, vb), b1 = _mm256_cmpeq_epi8(v1, vb);
    __m256i any = _mm256_or_si256(_mm256_or_si256(q0, q1),
                                  _mm256_or_si256(b0, b1));
    if (_mm256_testz_si256(any, any)) {
      prev_escaped = 0;
      qbits[w] = 0;
      sbits[w] = in_string;
      continue;
    }
    uint64_t q = (uint64_t)(uint32_t)_mm256_movemask_epi8(q0) |
                 ((uint64_t)(uint32_t)_mm256_movemask_epi8(q1) << 32);
    uint64_t b = (uint64_t)(uint32_t)_mm256_movemask_epi8(b0) |
                 ((uint64_t)(uint32_t)_mm256_movemask_epi8(b1) << 32);
    uint64_t esc = find_escaped(b, &prev_escaped);
    q &= ~esc;
    uint64_t S = prefix_xor64(q) ^ in_string;
    in_string = (uint64_t)(-(int64_t)(S >> 63));
    qbits[w] = q;
    sbits[w] = S;
  }
}
typedef void (*build_structural_fn)(const uint8_t*, int64_t, uint64_t*,
                                    uint64_t*);
static build_structural_fn build_structural_resolve() {
  // same runtime-dispatch posture as the CRC path: AVX2 instructions live
  // only behind the cpu check, the .so itself stays baseline-x86_64
  static build_structural_fn impl = nullptr;
  build_structural_fn fn = impl;
  if (!fn) {
    fn = __builtin_cpu_supports("avx2") ? build_structural_avx2
                                        : build_structural_sse2;
    impl = fn;
  }
  return fn;
}
static void build_structural(const uint8_t* s, int64_t len, uint64_t* qbits,
                             uint64_t* sbits) {
  build_structural_resolve()(s, len, qbits, sbits);
}
#else
static void build_structural(const uint8_t* s, int64_t len, uint64_t* qbits,
                             uint64_t* sbits) {
  RP_BUILD_STRUCTURAL_BODY(classify2_swar)
}
#endif

static inline int64_t next_set_bit(const uint64_t* words, int64_t len,
                                   int64_t from) {
  if (from >= len) return -1;
  int64_t w = from >> 6;
  uint64_t cur = words[w] & (~0ULL << (from & 63));
  for (;;) {
    if (cur) return (w << 6) + __builtin_ctzll(cur);
    if (((++w) << 6) >= len) return -1;
    cur = words[w];
  }
}

// skip_string twin over the quote bitmap: i at the opening quote. The next
// quote BIT is the closing quote by construction (escaped quotes are
// masked out of qbits; operators between them are irrelevant here).
static inline int64_t skip_string_idx(int64_t i, int64_t end,
                                      const uint64_t* qbits) {
  int64_t close = next_set_bit(qbits, end, i + 1);
  return close < 0 ? end : close + 1;
}

// skip_value twin: containers walk lazily classified operator words
// (masked by the stored string-interior bits), strings jump via the quote
// bitmap, primitives byte-scan exactly like the scalar walker (their
// tokens are a few bytes and the scalar stop set must be honored
// byte-for-byte).
static int64_t skip_value_idx(const uint8_t* s, int64_t i, int64_t end,
                              const uint64_t* qbits, const uint64_t* sbits) {
  i = skip_ws(s, i, end);
  if (i >= end) return end;
  uint8_t c = s[i];
  if (c == '"') return skip_string_idx(i, end, qbits);
  if (c == '{' || c == '[') {
    int64_t depth = 0;
    int64_t nwords = (end + 63) >> 6;
    uint64_t first_mask = ~0ULL << (i & 63);
    for (int64_t w = i >> 6; w < nwords; w++) {
      uint64_t ow = classify_op_word(s, w, end) & ~sbits[w] & first_mask;
      first_mask = ~0ULL;
      while (ow) {
        int64_t p = (w << 6) + __builtin_ctzll(ow);
        ow &= ow - 1;
        uint8_t pc = s[p];
        if (pc == '{' || pc == '[') {
          depth++;
        } else if (pc == '}' || pc == ']') {
          depth--;
          if (depth == 0) return p + 1;
        }
        // ':' and ',' are structural but depth-neutral
      }
    }
    return end;
  }
  while (i < end && c != ',' && c != '}' && c != ']' && c != ' ' &&
         c != '\t' && c != '\n' && c != '\r') {
    i++;
    if (i < end) c = s[i];
  }
  return i;
}

// classify_value twin; token typing shares the scalar rules verbatim.
static int32_t classify_value_idx(const uint8_t* s, int64_t i, int64_t end,
                                  const uint64_t* qbits,
                                  const uint64_t* sbits, int64_t* vs,
                                  int64_t* ve) {
  if (i >= end) return 0;
  uint8_t c = s[i];
  if (c == '"') {
    int64_t j = skip_string_idx(i, end, qbits);
    *vs = i + 1;
    *ve = j - 1;
    return 1;
  }
  if (c == '{') {
    *vs = i;
    *ve = skip_value_idx(s, i, end, qbits, sbits);
    return 6;
  }
  if (c == '[') {
    *vs = i;
    *ve = skip_value_idx(s, i, end, qbits, sbits);
    return 7;
  }
  int64_t j = skip_value_idx(s, i, end, qbits, sbits);
  *vs = i;
  *ve = j;
  int64_t tl = j - i;
  if (tl == 4 && std::memcmp(s + i, "true", 4) == 0) return 3;
  if (tl == 5 && std::memcmp(s + i, "false", 5) == 0) return 4;
  if (tl == 4 && std::memcmp(s + i, "null", 4) == 0) return 5;
  return 2;
}

// Stage 2: find_in_record with the three skip primitives swapped for their
// structural-index twins. The control flow is line-for-line the scalar
// walker's, so the two walks cannot diverge on ANY input — well-formed or
// malformed — except through the skip primitives, whose equivalence the
// parity suite pins (escaped quotes, backslash runs, unterminated
// strings, truncated records).
static void find2_in_record(const uint8_t* s, int64_t end,
                            const uint64_t* qbits, const uint64_t* sbits,
                            const char* paths_blob, const int32_t* path_off,
                            const int32_t* path_lens, int32_t k,
                            int8_t* trow, int64_t* vrow, int64_t* erow) {
  std::memset(trow, 0, (size_t)k);
  if (end <= 0) return;
  int64_t i = skip_ws(s, 0, end);
  if (i >= end || s[i] != '{') return;
  i++;
  int32_t found = 0;
  for (;;) {
    i = skip_ws(s, i, end);
    if (i >= end || s[i] == '}') break;
    if (s[i] != '"') break;  // malformed
    int64_t kstart = i + 1;
    i = skip_string_idx(i, end, qbits);
    int64_t kend = i - 1;
    i = skip_ws(s, i, end);
    if (i >= end || s[i] != ':') break;
    i++;
    i = skip_ws(s, i, end);
    int64_t klen = kend - kstart;
    bool matched = false;
    for (int32_t p = 0; p < k; p++) {
      if (trow[p] != 0) continue;  // first occurrence wins
      if (klen == path_lens[p] &&
          std::memcmp(s + kstart, paths_blob + path_off[p],
                      (size_t)path_lens[p]) == 0) {
        int64_t vs, ve;
        int32_t t = classify_value_idx(s, i, end, qbits, sbits, &vs, &ve);
        if (t == 0) break;
        trow[p] = (int8_t)t;
        vrow[p] = vs;
        erow[p] = ve;
        matched = true;
        found++;
        i = (t == 1) ? ve + 1 : ve;
        break;
      }
    }
    if (!matched) i = skip_value_idx(s, i, end, qbits, sbits);
    i = skip_ws(s, i, end);
    if (i < end && s[i] == ',') i++;
    if (found == k) break;  // everything located
  }
}

// Structural-index fused parse: the launch's payload bytes cross the
// native boundary ONCE, as a table of per-batch source pointers — no
// Python-side b"".join. When `joined_out` is given (passthrough plans,
// whose zero-copy harvest gathers output bytes from the blob) each
// payload is memcpy'd in first and parsed cache-hot from the copy; when
// NULL (projection plans — nothing downstream ever reads the raw bytes
// again) records parse straight from the source buffers and the blob is
// never built. val_off is absolute into the (possibly virtual)
// concatenation either way, so the index tables are identical to
// rp_explode_find's. Returns records parsed (== sum(counts) on success),
// or -1 on scratch allocation failure.
int64_t rp_explode_find2(const uint8_t* const* payloads,
                         const int32_t* payload_len, const int32_t* counts,
                         int32_t n_batches, uint8_t* joined_out,
                         const char* paths_blob, const int32_t* path_off,
                         const int32_t* path_lens, int32_t k,
                         int64_t* val_off, int32_t* val_len, int8_t* types,
                         int64_t* vs_arr, int64_t* ve_arr) {
  // one scratch bitmap pair sized to the largest payload (a record value
  // can never outgrow its batch payload), reused cache-hot per record
  int64_t max_words = 1;
  for (int32_t b = 0; b < n_batches; b++) {
    int64_t w = ((int64_t)payload_len[b] + 63) >> 6;
    if (w > max_words) max_words = w;
  }
  uint64_t* qbits = (uint64_t*)std::malloc((size_t)max_words * 8);
  uint64_t* sbits = (uint64_t*)std::malloc((size_t)max_words * 8);
  if (!qbits || !sbits) {
    std::free(qbits);
    std::free(sbits);
    return -1;
  }
  int64_t r = 0;
  int64_t base = 0;
  for (int32_t b = 0; b < n_batches; b++) {
    const uint8_t* src = payloads[b];
    if (joined_out) {
      std::memcpy(joined_out + base, src, (size_t)payload_len[b]);
      src = joined_out + base;  // parse the copy while it is cache-hot
    }
    const uint8_t* p = src;
    const uint8_t* end = p + payload_len[b];
    for (int32_t i = 0; i < counts[b]; i++, r++) {
      const uint8_t* value;
      int64_t vlen;
      if (!parse_one_record(&p, end, &value, &vlen)) {
        std::free(qbits);
        std::free(sbits);
        return r;
      }
      val_off[r] = base + (value - src);
      if (vlen < 0) {
        val_len[r] = -1;
        std::memset(types + r * k, 0, (size_t)k);
      } else {
        val_len[r] = (int32_t)vlen;
        build_structural(value, vlen, qbits, sbits);
        find2_in_record(value, vlen, qbits, sbits, paths_blob, path_off,
                        path_lens, k, types + r * k, vs_arr + r * k,
                        ve_arr + r * k);
      }
    }
    base += payload_len[b];
  }
  std::free(qbits);
  std::free(sbits);
  return r;
}

// Fused extraction: every predicate input column AND (optionally) the
// packed projection rows gathered from the span tables in ONE
// record-major pass — replaces the per-column gather crossings, the
// separate rp_project_rows crossing and the numpy pad concatenations.
// Record bytes resolve against the per-batch source buffers (the same
// pointer table rp_explode_find2 consumed), so no joined blob is needed.
// pred_descs is [n_pred, 4] int32 {kind: 0 num, 1 str, 2 exists; span
// col; w; unused}; pred_ptrs holds the outputs in desc order with
// per-kind arity num=3 (f32, i32, flags), str=2 (bytes [n_pad, w], vlen
// i32), exists=1 (u8); rows [n, n_pad) get the staged extractors' exact
// pad semantics (zeros; str vlen -1). proj_descs/proj_rows/proj_ok (may
// be empty/NULL) follow rp_project_rows' desc layout and byte semantics.
void rp_extract_cols2(const uint8_t* const* payloads,
                      const int32_t* payload_len, const int32_t* counts,
                      int32_t n_batches, const int64_t* val_off,
                      const int32_t* val_len, const int8_t* types,
                      const int64_t* vs, const int64_t* ve, int32_t k,
                      const int32_t* pred_descs, int32_t n_pred,
                      void** pred_ptrs, int64_t n_pad,
                      const int32_t* proj_descs, int32_t n_proj,
                      int32_t r_out, uint8_t* proj_rows, uint8_t* proj_ok) {
  int64_t r = 0;
  int64_t base = 0;
  for (int32_t b = 0; b < n_batches; b++) {
    const uint8_t* buf = payloads[b];
    for (int32_t i = 0; i < counts[b]; i++, r++) {
      // null values (val_len -1) keep rec at the batch buffer: their
      // types row is all 0, so every extractor below emits "absent"
      // without dereferencing the span
      const uint8_t* rec = buf + (val_off[r] - base);
      const int8_t* trow = types + r * k;
      const int64_t* vrow = vs + r * k;
      const int64_t* erow = ve + r * k;
      int32_t pi = 0;
      for (int32_t d = 0; d < n_pred; d++) {
        const int32_t* de = pred_descs + d * 4;
        int32_t kind = de[0], col = de[1], w = de[2];
        if (kind == 0) {  // num: (f32, i32, flags) — rp_gather_num parity
          num_from_span(rec, trow[col], vrow[col], erow[col],
                        (float*)pred_ptrs[pi] + r,
                        (int32_t*)pred_ptrs[pi + 1] + r,
                        (uint8_t*)pred_ptrs[pi + 2] + r);
          pi += 3;
        } else if (kind == 1) {  // str — rp_gather_str parity
          uint8_t* dst = (uint8_t*)pred_ptrs[pi] + r * (int64_t)w;
          int32_t* out_vlen = (int32_t*)pred_ptrs[pi + 1];
          std::memset(dst, 0, (size_t)w);
          if (trow[col] != 1) {
            out_vlen[r] = -1;
          } else {
            int64_t vlen = erow[col] - vrow[col];
            if (vlen < 0) vlen = 0;  // unterminated: empty-but-present
            if (vlen > (1 << 30)) vlen = 1 << 30;
            out_vlen[r] = (int32_t)vlen;
            int64_t cp = vlen < w ? vlen : w;
            std::memcpy(dst, rec + vrow[col], (size_t)cp);
          }
          pi += 2;
        } else {  // exists
          ((uint8_t*)pred_ptrs[pi])[r] = trow[col] != 0;
          pi += 1;
        }
      }
      if (n_proj > 0) {
        project_one_row(rec, trow, vrow, erow, proj_descs, n_proj, r_out,
                        proj_rows + r * (int64_t)r_out, proj_ok + r);
      }
    }
    base += payload_len[b];
  }
  if (n_pad > r) {
    int64_t n = r;
    int64_t pad = n_pad - n;
    int32_t pi = 0;
    for (int32_t d = 0; d < n_pred; d++) {
      const int32_t* de = pred_descs + d * 4;
      int32_t kind = de[0], w = de[2];
      if (kind == 0) {
        std::memset((float*)pred_ptrs[pi] + n, 0, (size_t)pad * 4);
        std::memset((int32_t*)pred_ptrs[pi + 1] + n, 0, (size_t)pad * 4);
        std::memset((uint8_t*)pred_ptrs[pi + 2] + n, 0, (size_t)pad);
        pi += 3;
      } else if (kind == 1) {
        std::memset((uint8_t*)pred_ptrs[pi] + n * (int64_t)w, 0,
                    (size_t)(pad * w));
        int32_t* vl = (int32_t*)pred_ptrs[pi + 1];
        for (int64_t j = n; j < n_pad; j++) vl[j] = -1;
        pi += 2;
      } else {
        std::memset((uint8_t*)pred_ptrs[pi] + n, 0, (size_t)pad);
        pi += 1;
      }
    }
  }
}

// Presence-only column (exists()): 1 when the path resolves to any value.
int64_t rp_extract_exists(const uint8_t* joined, const int64_t* offsets,
                          const int32_t* sizes, int64_t n, const char* path,
                          int32_t path_len, uint8_t* out) {
  int64_t hits = 0;
  for (int64_t i = 0; i < n; i++) {
    out[i] = 0;
    int32_t sz = sizes[i];
    if (sz <= 0) continue;
    int64_t vs, ve;
    if (rp_json_find(joined + offsets[i], sz, path, path_len, &vs, &ve) != 0) {
      out[i] = 1;
      hits++;
    }
  }
  return hits;
}

}  // extern "C"
